"""HBM traffic auditor tests (analysis/traffic.py + analysis/budgets.py).

Fast tier: the analytic floor decomposition reproduces PERF.md's
hand-computed 124M B=8 numbers (and bench_decode.py's recorded floor
arithmetic), classification/budget logic against canned inputs.

Slow tier: compile the real decode window at audit size, gate it
against its checked-in budget, and re-introduce the PR 6
closed-over-model bug — the budget gate (not just the dequant rule)
must trip on it, from both directions: the weight stream vanishing
from the entry interface AND the executable bloating with baked-in
constants.
"""

import dataclasses

import pytest

from midgpt_tpu.analysis.budgets import (
    AUDIT_GEOMETRY,
    BUDGETS,
    budget_for,
    check_budget,
    geometry_key,
)
from midgpt_tpu.analysis.traffic import (
    TrafficReport,
    floor_decomposition,
    floor_table_markdown,
    parse_large_constants,
    traffic_report,
    weight_stream_bytes,
)
from midgpt_tpu.config import get_config


# ---------------------------------------------------------------------------
# analytic floor: reproduce PERF.md's decomposition
# ---------------------------------------------------------------------------


def test_floor_reproduces_perf_124m_decomposition():
    """PERF.md r5: 124M B=8, mean 640 live tokens, ~800 GB/s ->
    ~0.31 ms weights. The auditor must land within 5%."""
    cfg = get_config("openwebtext").model
    d = floor_decomposition(cfg, slots=8, live_tokens=640)
    assert abs(d["weights_floor_ms"] - 0.31) / 0.31 < 0.05
    # KV: scripts/bench_decode.py's recorded floor streams K AND V
    # (both are read every step); PERF's r5 prose "~0.12 ms" counted
    # the pair as one plane. Both conventions must be reproduced: the
    # honest stream within 5% of 2x the prose figure, and the prose
    # figure as exactly half the reported stream.
    assert abs(d["kv_floor_ms"] - 2 * 0.12) / (2 * 0.12) < 0.05
    assert abs(d["kv_floor_ms"] / 2 - 0.12) / 0.12 < 0.05
    # the bench_decode formula, verbatim
    expect_kv = cfg.n_layer * 8 * cfg.kv_heads * 640 * cfg.head_dim * 2 * 2
    assert d["kv_bytes_per_step"] == expect_kv


def test_floor_reproduces_perf_quant_weights():
    """PERF.md PR 6: int8 moves the 124M weight stream 0.31 -> ~0.155."""
    cfg = get_config("openwebtext").model
    d = floor_decomposition(cfg, slots=8, live_tokens=640, quant=True)
    assert abs(d["weights_floor_ms"] - 0.155) / 0.155 < 0.05


def test_weight_stream_matches_count_params():
    """The analytic weight stream is count_params(model) * 2 at bf16 —
    bench_decode.py's floor numerator — bit-exactly at audit size."""
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.models.gpt import GPT, count_params
    from midgpt_tpu.pytree import cast_floating

    cfg = dataclasses.replace(
        get_config("openwebtext").model,
        n_layer=2, block_size=256, vocab_size=1024,
    )
    model = cast_floating(GPT.init(jax.random.PRNGKey(0), cfg), jnp.bfloat16)
    assert weight_stream_bytes(cfg) == count_params(model) * 2


def test_tp_divides_per_chip_streams():
    cfg = get_config("openwebtext").model
    d1 = floor_decomposition(cfg, slots=8, live_tokens=640)
    d2 = floor_decomposition(cfg, slots=8, live_tokens=640, tp_degree=2)
    assert d2["weights_bytes_per_step"] == d1["weights_bytes_per_step"] // 2
    assert d2["kv_bytes_per_step"] == d1["kv_bytes_per_step"] // 2


def test_floor_table_renders():
    cfg = get_config("openwebtext").model
    rows = [
        floor_decomposition(cfg, slots=8, live_tokens=640),
        floor_decomposition(cfg, slots=8, live_tokens=640, quant=True),
    ]
    md = floor_table_markdown(rows)
    assert "| B=8 live=640 bf16 |" in md
    assert "0.309" in md and "0.155" in md


# ---------------------------------------------------------------------------
# classification + budget logic (canned inputs, jax-free)
# ---------------------------------------------------------------------------

_CANNED_HLO = """\
HloModule probe, input_output_alias={ {0}: (1, {}, may-alias) }, \
entry_computation_layout={(bf16[2,768,2304]{2,1,0}, s8[2,3072,768]{2,1,0}, \
bf16[2,8,12,64,16]{4,3,2,1,0}, f32[4,1024]{1,0}, s32[4,16]{1,0}, \
f32[99,99]{1,0})->f32[4,1024]{1,0}}

ENTRY main {
  c0 = bf16[1024,768]{1,0} constant({...})
  c1 = f32[16]{0} constant({...})
  ROOT t = f32[4,1024]{1,0} parameter(3)
}
"""


def _canned_report(**overrides):
    keys = {
        "weights": {
            ("bf16", (2, 768, 2304)), ("s8", (2, 3072, 768)),
        },
        "kv": {("bf16", (2, 8, 12, 64, 16))},
        "logits": {("f32", (4, 1024))},
    }
    kw = dict(
        program="decode_window", stream_keys=keys, window_steps=4,
        comms_bytes=0,
    )
    kw.update(overrides)
    return traffic_report(_CANNED_HLO, **kw)


def test_classification_bins_by_dtype_and_shape():
    rep = _canned_report()
    assert rep.streams["weights"] == (
        2 * 768 * 2304 * 2 + 2 * 3072 * 768 * 1
    )
    assert rep.streams["kv"] == 2 * 8 * 12 * 64 * 16 * 2
    assert rep.streams["logits"] == 4 * 1024 * 4
    assert rep.streams["control"] == 4 * 16 * 4
    # the f32[99,99] matches nothing -> surfaced, not silently binned
    assert rep.unclassified == (("f32", (99, 99)),)
    # the big bf16 constant is counted; the 16-element one is noise
    assert rep.streams["constants"] == 1024 * 768 * 2
    assert rep.weights_bytes_per_dispatch == rep.streams["weights"] * 4


def test_parse_large_constants_threshold():
    consts = parse_large_constants(_CANNED_HLO, min_bytes=4096)
    assert consts == [("bf16", (1024, 768))]
    assert ("f32", (16,)) in parse_large_constants(
        _CANNED_HLO, min_bytes=1
    )


def _mk_report(weights, kv=1000, logits=100, constants=0, comms=0,
               unclassified=()):
    return TrafficReport(
        program="probe",
        streams={
            "weights": weights, "kv": kv, "logits": logits,
            "control": 0, "constants": constants,
        },
        window_steps=1,
        comms_bytes=comms,
        unclassified=tuple(unclassified),
    )


_BUDGET = {
    "weights": 10000, "kv": 1000, "logits": 100,
    "constants_max": 500, "comms_max": 50,
}


def test_budget_passes_in_band():
    assert check_budget(_mk_report(weights=10100), _BUDGET) == []


def test_budget_trips_on_missing_weight_stream():
    """The PR 6 signature: weights leave the entry interface."""
    bad = check_budget(_mk_report(weights=0), _BUDGET)
    assert any("weights stream" in v for v in bad)


def test_budget_trips_on_doubled_weight_stream():
    bad = check_budget(_mk_report(weights=20000), _BUDGET)
    assert any("weights stream" in v for v in bad)


def test_budget_trips_on_baked_constants():
    bad = check_budget(
        _mk_report(weights=10000, constants=100000), _BUDGET
    )
    assert any("constant" in v for v in bad)


def test_budget_trips_on_comms_blowup():
    bad = check_budget(_mk_report(weights=10000, comms=5000), _BUDGET)
    assert any("collective" in v for v in bad)


def test_budget_trips_on_unclassified_param():
    bad = check_budget(
        _mk_report(weights=10000, unclassified=[("f32", (99, 99))]),
        _BUDGET,
    )
    assert any("unclassified" in v for v in bad)


def test_geometry_keys():
    assert geometry_key(None) == "single"
    assert geometry_key({}) == "single"
    assert geometry_key({"tensor": 2, "replica": 2}) == "replica2,tensor2"
    assert geometry_key({"tensor": 2, "replica": 1}) == "tensor2"


def test_budget_table_covers_all_programs_and_precisions():
    programs = {"decode_window", "prefill_chunk", "verify_program"}
    # SP prefill only exists on sharded meshes (tensor > 1), so its cells
    # appear under the tp geometry only.
    sharded = programs | {"prefill_chunk_sp"}
    for geom in ("single", "replica2,tensor2"):
        want = sharded if geom == "replica2,tensor2" else programs
        for precision in ("bf16", "int8"):
            have = {
                p for (p, q, g) in BUDGETS
                if q == precision and g == geom
            }
            assert have == want, (precision, geom, have)
    assert AUDIT_GEOMETRY["config"] == "openwebtext"


# ---------------------------------------------------------------------------
# slow tier: real compiles — the gate passes on the tree, trips on the
# PR 6 closure
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_audit_traffic_within_checked_in_budget():
    from midgpt_tpu.analysis.harness import audit_decode_window

    _, report, traf = audit_decode_window(
        "openwebtext", slots=4, window=4, page_size=16, traffic=True
    )
    assert report.ok
    budget = budget_for("decode_window", "bf16", "single")
    assert check_budget(traf, budget) == [], check_budget(traf, budget)


@pytest.mark.slow
def test_budget_cells_invariant_to_banding():
    """Banding moves ZERO bytes (ISSUE 20): the banded PV fold slices
    the same streams the unbanded reduction read — each K/V byte still
    crosses HBM exactly once per pass — so every decode-window traffic
    cell must land in the SAME checked-in budget band with a genuinely
    multi-banded plan forced as with the auto plan (one band at this
    geometry), and the two audits' classified per-stream totals must be
    byte-identical."""
    import midgpt_tpu.ops.paged_attn as pa
    from midgpt_tpu.analysis.harness import audit_decode_window

    _, report, traf = audit_decode_window(
        "openwebtext", slots=4, window=4, page_size=16, traffic=True
    )
    assert report.ok
    old = pa._FORCE_BAND_PAGES
    pa._FORCE_BAND_PAGES = 2
    try:
        _, report_b, traf_b = audit_decode_window(
            "openwebtext", slots=4, window=4, page_size=16, traffic=True
        )
    finally:
        pa._FORCE_BAND_PAGES = old
    assert report_b.ok
    budget = budget_for("decode_window", "bf16", "single")
    assert check_budget(traf_b, budget) == [], check_budget(traf_b, budget)
    assert dict(traf_b.streams) == dict(traf.streams), (
        traf_b.streams, traf.streams
    )


@pytest.mark.slow
def test_model_closure_trips_budget_gate():
    """Re-introduce the PR 6 bug: a decode window that CLOSES OVER the
    model instead of taking it as an entry parameter. The weights leave
    the program interface (below the weights band) and reappear as
    baked-in constants (above the constants cap) — the budget gate must
    trip on BOTH, independent of any HLO shape pattern."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.analysis.harness import (
        _serving_audit_setup, serving_stream_keys,
    )
    from midgpt_tpu.config import ModelConfig
    from midgpt_tpu.serving.engine import make_decode_window

    cfg = get_config("openwebtext")
    # extra-tiny geometry: the closure bakes every weight into the
    # compiled module's TEXT, so keep the model small
    tiny = dataclasses.replace(
        cfg,
        model=ModelConfig(
            block_size=64, vocab_size=128, n_layer=1, n_head=4,
            n_embd=64, dropout=0.0, remat="none", scan_unroll=1,
        ),
    )
    slots, window, page_size = 2, 2, 16
    model_cfg, mesh, model, pmax, pool, logits, _, _ = (
        _serving_audit_setup(
            tiny, slots=slots, page_size=page_size, shrink=False
        )
    )
    keys = serving_stream_keys(model, pool, logits)
    window_fn = make_decode_window(
        model, slots=slots, window=window, pmax=pmax,
        rope_len=model_cfg.block_size,
    )
    i32 = lambda *s: np.zeros(s, np.int32)  # noqa: E731
    args = (
        pool, logits, i32(slots, pmax), i32(slots),
        np.zeros((slots,), bool), i32(slots), i32(slots), i32(slots),
        i32(slots), jax.random.PRNGKey(1),
    )

    # healthy program: model as entry parameter -> measure its budget
    healthy_hlo = window_fn.lower(model, *args).compile().as_text()
    healthy = traffic_report(
        healthy_hlo, program="decode_window", stream_keys=keys,
        window_steps=window,
    )
    budget = {
        "weights": healthy.streams["weights"],
        "kv": healthy.streams["kv"],
        "logits": healthy.streams["logits"],
        "constants_max": max(4096, healthy.streams["constants"]),
    }
    assert healthy.streams["weights"] > 0
    assert check_budget(healthy, budget) == []

    # the PR 6 bug, verbatim: close over the model
    closed = jax.jit(lambda *a: window_fn(model, *a))
    bad_hlo = closed.lower(*args).compile().as_text()
    bad = traffic_report(
        bad_hlo, program="decode_window", stream_keys=keys,
        window_steps=window,
    )
    violations = check_budget(bad, budget)
    assert any("weights stream" in v for v in violations), violations
    assert any("constant" in v for v in violations), violations


# ---------------------------------------------------------------------------
# int8-quantized KV pool cells (PR 9)
# ---------------------------------------------------------------------------


def test_kv8_budget_cells_exist_for_every_program():
    from midgpt_tpu.analysis.budgets import precision_key

    for prog in ("decode_window", "prefill_chunk", "verify_program"):
        for prec in ("bf16", "int8"):
            for geom in ("single", "replica2,tensor2"):
                cell = budget_for(prog, precision_key(prec, True), geom)
                assert cell is not None, (prog, prec, geom)
                assert "kv" in cell and "constants_max" in cell


def test_kv8_cells_carry_half_the_bf16_kv_stream():
    """The point of the int8 pool, in budget arithmetic: every kv8 cell's
    KV stream is the bf16 cell's payload halved plus the f32
    per-(page, KV-head) scale planes — and the scale overhead is small
    (< 1% of the payload at the audit geometry). The bf16 cells are
    untouched."""
    from midgpt_tpu.analysis.budgets import precision_key

    for prog in ("decode_window", "prefill_chunk", "verify_program"):
        for geom in ("single", "replica2,tensor2"):
            for prec in ("bf16", "int8"):
                base = budget_for(prog, prec, geom)
                kv8 = budget_for(prog, precision_key(prec, True), geom)
                scales = kv8["kv"] - base["kv"] // 2
                assert 0 < scales < base["kv"] // 100, (
                    prog, prec, geom, kv8["kv"], base["kv"]
                )
                # weights are orthogonal: kv-quant must not move them
                assert kv8["weights"] == base["weights"]


def test_precision_key():
    from midgpt_tpu.analysis.budgets import precision_key

    assert precision_key("bf16") == "bf16"
    assert precision_key("int8", False) == "int8"
    assert precision_key("bf16", True) == "bf16-kv8"
    assert precision_key("int8", True) == "int8-kv8"


def test_floor_decomposition_kv_quant_halves_kv_stream():
    """The analytic roofline with the int8 pool: KV bytes drop to half
    plus the per-page scale term, moving the 124M B=8 int8-weights floor
    from ~0.39 (0.155 w + 0.236 kv) toward ~0.27 ms/step (0.155 +
    0.118) — the PR 9 target arithmetic (PERF.md)."""
    cfg = get_config("openwebtext").model
    base = floor_decomposition(cfg, slots=8, live_tokens=640, quant=True)
    kv8 = floor_decomposition(
        cfg, slots=8, live_tokens=640, quant=True, kv_quant=True
    )
    assert kv8["kv_quant"] is True
    payload_half = base["kv_bytes_per_step"] // 2
    scales = kv8["kv_bytes_per_step"] - payload_half
    assert 0 < scales < base["kv_bytes_per_step"] // 50
    assert kv8["weights_bytes_per_step"] == base["weights_bytes_per_step"]
    # the headline: int8 weights + int8 KV lands near the ~0.27 floor
    assert abs(kv8["floor_ms_per_step"] - 0.28) < 0.03
    assert abs(base["floor_ms_per_step"] - 0.39) < 0.03
    # the floor table renders the kv8 tag
    table = floor_table_markdown([kv8])
    assert "kv8" in table
