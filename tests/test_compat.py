"""Direct unit tests for the midgpt_tpu.compat shims (and the related
per-module version guards they document): the new-style ``shard_map``
surface routed onto whatever this jax pin provides, the
``tpu_compiler_params`` dataclass rename, and the pvary/pcast varying-
promotion fallback in parallel.pipeline. Until PR 5 these were only
exercised transitively through the 54 repaired tier-1 tests — a shim
regression surfaced as a wall of unrelated failures instead of one
pointed one."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu import compat
from midgpt_tpu.compat import shard_map, tpu_compiler_params


def _mesh1d():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("x",))


# ---------------------------------------------------------------------------
# shard_map: the new-style surface on any pin
# ---------------------------------------------------------------------------


def test_shard_map_basic_map_and_collective():
    """The plain surface (mesh/in_specs/out_specs keywords) maps per-shard
    and runs collectives — on the old pin this must route through
    jax.experimental.shard_map with check_vma translated to check_rep."""
    mesh = _mesh1d()
    double = shard_map(
        lambda a: a * 2, mesh=mesh, in_specs=(P("x"),), out_specs=P("x")
    )
    np.testing.assert_array_equal(
        np.asarray(double(jnp.arange(8))), 2 * np.arange(8)
    )
    # a replicated output through psum passes the replication check
    # (check_vma=True is the default — the renamed check_rep)
    total = shard_map(
        lambda a: jax.lax.psum(a, "x"),
        mesh=mesh,
        in_specs=(P("x"),),
        out_specs=P(),
        check_vma=True,
    )
    np.testing.assert_allclose(np.asarray(total(jnp.arange(8.0))), [28.0])


def test_shard_map_axis_names_with_axis_index():
    """``axis_names`` (the partial-manual surface) with a body that takes
    ``jax.lax.axis_index`` — exactly the combination 0.4.x's experimental
    partial-auto lowering rejects (PartitionId in the SPMD partitioner),
    which is why the shim runs it fully manual there. The observable
    contract is value-level: per-shard axis indices come out right."""
    mesh = _mesh1d()
    f = shard_map(
        lambda a: a + jax.lax.axis_index("x").astype(a.dtype),
        mesh=mesh,
        in_specs=(P("x"),),
        out_specs=P("x"),
        axis_names={"x"},
    )
    np.testing.assert_array_equal(
        np.asarray(f(jnp.zeros((8,), jnp.int32))), np.arange(8)
    )


def test_shard_map_old_pin_translation_kwargs():
    """On a pin without ``jax.shard_map`` the shim must call the
    experimental entry point with the TRANSLATED kwargs: check_vma ->
    check_rep, and axis_names forcing check_rep off (the partial-auto
    semantics predate the replication checker). Asserted by intercepting
    the experimental symbol the shim dispatches to."""
    if compat._HAS_TOP_LEVEL:
        pytest.skip("new jax: the shim passes through to jax.shard_map")
    seen = {}

    def fake(f, *, mesh, in_specs, out_specs, check_rep):
        seen["check_rep"] = check_rep
        return lambda *a: a[0]

    orig = compat._shard_map_experimental
    compat._shard_map_experimental = fake
    try:
        shard_map(
            lambda a: a, mesh=None, in_specs=(P(),), out_specs=P(),
            check_vma=True,
        )(0)
        assert seen["check_rep"] is True  # check_vma -> check_rep
        shard_map(
            lambda a: a, mesh=None, in_specs=(P(),), out_specs=P(),
            check_vma=True, axis_names={"x"},
        )(0)
        assert seen["check_rep"] is False  # axis_names forces it off
    finally:
        compat._shard_map_experimental = orig


# ---------------------------------------------------------------------------
# tpu_compiler_params: the CompilerParams/TPUCompilerParams rename
# ---------------------------------------------------------------------------


def test_tpu_compiler_params_constructs_on_this_pin():
    p = tpu_compiler_params(
        dimension_semantics=("parallel",), vmem_limit_bytes=1 << 20
    )
    # both the old and new dataclass expose the two fields the kernels use
    assert p.dimension_semantics == ("parallel",)
    assert p.vmem_limit_bytes == 1 << 20


def test_tpu_compiler_params_picks_whichever_class_exists():
    from jax.experimental.pallas import tpu as pltpu

    expected = getattr(pltpu, "CompilerParams", None) or (
        pltpu.TPUCompilerParams
    )
    assert isinstance(tpu_compiler_params(), expected)


# ---------------------------------------------------------------------------
# pvary/pcast fallback (parallel.pipeline._to_varying)
# ---------------------------------------------------------------------------


def test_to_varying_is_value_identity():
    """The varying-axes promotion is a type-system annotation in new jax
    and must be a value-level no-op on every pin — on jax without
    pcast/pvary (this 0.4.37 pin) the fallback is literal identity."""
    from midgpt_tpu.parallel.pipeline import _to_varying

    x = jnp.arange(6.0).reshape(2, 3)
    y = _to_varying(x, "pipeline")
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    if not hasattr(jax.lax, "pcast") and not hasattr(jax.lax, "pvary"):
        assert y is x  # the old-pin branch is exactly identity


def test_to_varying_inside_manual_region():
    """_to_varying composes inside a manual shard_map region (where the
    pipeline uses it): the promoted value feeds a collective without
    changing its contents."""
    mesh = _mesh1d()
    from midgpt_tpu.parallel.pipeline import _to_varying

    def body(a):
        return jax.lax.psum(_to_varying(a, "x"), "x")

    f = shard_map(
        body, mesh=mesh, in_specs=(P("x"),), out_specs=P(),
        check_vma=False,
    )
    np.testing.assert_allclose(np.asarray(f(jnp.arange(8.0))), [28.0])
