"""Model-level tests: shapes, causality, GQA/SwiGLU variants, tying,
parity of the batched forward against a per-sequence re-derivation of the
reference math (/root/reference/src/model.py:34-105)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT, count_params
from midgpt_tpu.models.layers import apply_rotary, rope_tables
from midgpt_tpu.ops.attention import naive_attention

CFG = ModelConfig(
    block_size=32, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def _model(cfg=CFG, seed=0):
    return GPT.init(jax.random.PRNGKey(seed), cfg)


def test_forward_shape_and_dtype():
    model = _model()
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = model(tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)


def test_causality():
    """Changing token t must not affect logits at positions < t."""
    model = _model()
    key = jax.random.PRNGKey(3)
    tokens = jax.random.randint(key, (1, 16), 0, CFG.vocab_size)
    logits = model(tokens)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % CFG.vocab_size)
    logits2 = model(tokens2)
    np.testing.assert_allclose(
        np.asarray(logits[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits[0, 10:]), np.asarray(logits2[0, 10:]))


def test_remat_matches_no_remat():
    cfg_full = dataclasses.replace(CFG, remat="full")
    model = _model()
    model_full = dataclasses.replace(model, config=cfg_full)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, CFG.vocab_size)
    np.testing.assert_allclose(
        np.asarray(model(tokens)), np.asarray(model_full(tokens)), atol=1e-5
    )


def test_init_only_weight_sharing():
    """Reference semantics (SURVEY.md 2.3): wte and lm_head start equal but
    are independent leaves."""
    model = _model()
    assert model.lm_head is not None
    np.testing.assert_array_equal(
        np.asarray(model.wte.weight), np.asarray(model.lm_head.weight.T)
    )
    leaves = jax.tree.leaves(model)
    n_all = sum(x.size for x in leaves)
    assert count_params(model) == n_all - model.lm_head.weight.size


def test_true_tying():
    cfg = dataclasses.replace(CFG, tie_embeddings=True)
    model = _model(cfg)
    assert model.lm_head is None
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    assert model(tokens).shape == (1, 8, cfg.vocab_size)


def test_gqa_and_swiglu_variant():
    cfg = dataclasses.replace(CFG, n_kv_head=2, mlp="swiglu", mlp_ratio=2.0)
    model = _model(cfg)
    # fused qkv: (H + 2*Hkv) * C = (4 + 4) * 8 = 64
    assert model.blocks.attn.wqkv.weight.shape == (2, 32, 64)
    assert model.blocks.mlp.w_gate is not None
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    assert model(tokens).shape == (2, 16, cfg.vocab_size)


def test_batched_forward_matches_reference_math():
    """Re-derive one attention layer the reference way (per-sequence,
    model.py:56-81) and compare with the batched Attention module."""
    cfg = CFG
    model = _model()
    attn = jax.tree.map(lambda x: x[0], model.blocks.attn)  # layer 0
    x = jax.random.normal(jax.random.PRNGKey(7), (3, 16, cfg.n_embd))

    out = attn(x, *rope_tables(cfg.head_dim, 16, cfg.rope_base), impl="naive")

    # reference-style single-sequence computation
    h, c = cfg.n_head, cfg.head_dim
    def one_seq(x_td):
        qkv = x_td @ np.asarray(attn.wqkv.weight)  # [T, 3D]
        q, k, v = np.split(qkv, 3, axis=-1)
        def heads(z):
            return np.transpose(z.reshape(16, h, c), (1, 0, 2))  # [H,T,C]
        q, k, v = heads(q), heads(k), heads(v)
        # QK layernorm (weight=1 at init, mean-subtract)
        def ln(z):
            mu = z.mean(-1, keepdims=True)
            zc = z - mu
            return zc / np.sqrt((zc ** 2).mean(-1, keepdims=True) + 1e-6)
        q, k = ln(q), ln(k)
        sin, cos = rope_tables(c, 16, cfg.rope_base)
        q = np.asarray(apply_rotary(jnp.asarray(q), sin, cos))
        k = np.asarray(apply_rotary(jnp.asarray(k), sin, cos))
        scores = q @ np.transpose(k, (0, 2, 1))
        mask = np.tril(np.ones((16, 16))) == 0
        scores = np.where(mask, -np.inf, scores)
        probs = jax.nn.softmax(jnp.asarray(scores / np.sqrt(c)), axis=-1)
        o = np.asarray(probs) @ v  # [H,T,C]
        o = np.transpose(o, (1, 0, 2)).reshape(16, h * c)
        return o @ np.asarray(attn.wo.weight)

    expected = np.stack([one_seq(np.asarray(x[i])) for i in range(3)])
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5)


def test_naive_attention_gqa_broadcast():
    """GQA result == MHA with explicitly repeated KV heads."""
    key = jax.random.PRNGKey(0)
    b, h, hkv, t, c = 2, 8, 2, 16, 8
    q = jax.random.normal(key, (b, h, t, c))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, t, c))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, t, c))
    out = naive_attention(q, k, v)
    k_rep = jnp.repeat(k, h // hkv, axis=1)
    v_rep = jnp.repeat(v, h // hkv, axis=1)
    out_rep = naive_attention(q, k_rep, v_rep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_rep), atol=1e-5)


def test_dropout_training_path():
    cfg = dataclasses.replace(CFG, dropout=0.1)
    model = _model(cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    l1 = model(tokens, key=jax.random.PRNGKey(0), deterministic=False)
    l2 = model(tokens, key=jax.random.PRNGKey(1), deterministic=False)
    assert not np.allclose(np.asarray(l1), np.asarray(l2))
    # deterministic forward ignores dropout
    l3 = model(tokens)
    l4 = model(tokens)
    np.testing.assert_array_equal(np.asarray(l3), np.asarray(l4))


@pytest.mark.parametrize("remat", ["none", "full", "dots"])
def test_remat_policies_agree(remat):
    """All remat policies are pure memory/compute tradeoffs — identical
    forwards and gradients."""
    cfg_r = dataclasses.replace(CFG, remat=remat)
    model = GPT.init(jax.random.PRNGKey(0), dataclasses.replace(CFG, remat="none"))
    model_r = dataclasses.replace(model, config=cfg_r)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab_size)
    y = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, CFG.vocab_size)

    def loss(m):
        import optax

        logits = m(x).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    ref = jax.jit(loss)(model)
    out = jax.jit(loss)(model_r)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-6)
    g_ref = jax.jit(jax.grad(loss))(model)
    g_out = jax.jit(jax.grad(loss))(model_r)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_scan_unroll_agrees():
    cfg_u = dataclasses.replace(CFG, scan_unroll=2, n_layer=4)
    cfg_1 = dataclasses.replace(CFG, scan_unroll=1, n_layer=4)
    model = GPT.init(jax.random.PRNGKey(0), cfg_1)
    model_u = dataclasses.replace(model, config=cfg_u)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab_size)
    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda m: m(x))(model_u)),
        np.asarray(jax.jit(lambda m: m(x))(model)),
        atol=1e-6,
    )
