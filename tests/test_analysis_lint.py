"""Unit tests for the AST TPU-footgun lint (analysis.pylint_pass), plus
the enforcement test that keeps the shipped tree lint-clean — the
"zero unwaived findings on midgpt_tpu/" acceptance bar, made permanent.
"""

import pathlib
import textwrap

import midgpt_tpu
from midgpt_tpu.analysis.pylint_pass import lint_paths, lint_source, unwaived


def _lint(src: str):
    return lint_source(textwrap.dedent(src), path="probe.py")


def _rules(findings):
    return [(f.rule, f.lineno) for f in findings if not f.waived]


# ---------------------------------------------------------------------------
# host-sync-in-jit
# ---------------------------------------------------------------------------


def test_item_in_jitted_function_flagged():
    fs = _lint(
        """
        import jax

        def step(state, x):
            return state, x.item()

        train = jax.jit(step, donate_argnums=(0,))
        """
    )
    assert _rules(fs) == [("host-sync-in-jit", 5)]


def test_host_sync_in_scan_body_flagged():
    fs = _lint(
        """
        import jax

        def body(carry, xs):
            v = jax.device_get(xs)
            return carry, v

        out = jax.lax.scan(body, 0, None)
        """
    )
    assert ("host-sync-in-jit", 5) in _rules(fs)


def test_np_asarray_in_traced_code_flagged():
    fs = _lint(
        """
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            return np.asarray(x) + n
        """
    )
    assert ("host-sync-in-jit", 8) in _rules(fs)


def test_transitive_reference_into_jit_is_traced():
    """jax.jit(wrapped) -> wrapped references step_fn -> step_fn's body
    is traced too (the make_train_step shape)."""
    fs = _lint(
        """
        import jax

        def step_fn(state, x):
            return state, x.item()

        def wrapped(state, x):
            return step_fn(state, x)

        train = jax.jit(wrapped, donate_argnums=(0,))
        """
    )
    assert ("host-sync-in-jit", 5) in _rules(fs)


def test_host_code_not_flagged():
    fs = _lint(
        """
        import numpy as np

        def load(path):
            x = np.asarray(open(path).read())
            return x.item()
        """
    )
    assert _rules(fs) == []


def test_jnp_asarray_not_flagged():
    fs = _lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.asarray(x)
        """
    )
    assert _rules(fs) == []


# ---------------------------------------------------------------------------
# unknown-mesh-axis
# ---------------------------------------------------------------------------


def test_unknown_axis_literal_flagged():
    fs = _lint(
        """
        from jax.sharding import PartitionSpec as P

        spec = P("fsdp", "tenzor")
        """
    )
    assert _rules(fs) == [("unknown-mesh-axis", 4)]


def test_known_axes_and_tuples_pass():
    fs = _lint(
        """
        from jax.sharding import PartitionSpec as P

        a = P(None, ("replica", "fsdp"), "sequence")
        b = P("pipeline", "fsdp", "tensor")
        """
    )
    assert _rules(fs) == []


def test_non_spec_strings_not_checked():
    fs = _lint(
        """
        def shard_act(x, *names):
            return x

        y = shard_act(None, "batch", "seq", "embed")  # logical, not mesh
        """
    )
    assert _rules(fs) == []


# ---------------------------------------------------------------------------
# missing-donate
# ---------------------------------------------------------------------------


def test_jit_on_state_fn_without_donation_flagged():
    fs = _lint(
        """
        import jax

        def step(state, x):
            return state

        train = jax.jit(step)
        """
    )
    assert _rules(fs) == [("missing-donate", 7)]


def test_jit_with_donation_passes():
    fs = _lint(
        """
        import jax

        def step(state, x):
            return state

        train = jax.jit(step, donate_argnums=(0,))
        """
    )
    assert _rules(fs) == []


def test_non_state_jit_not_flagged():
    fs = _lint(
        """
        import jax

        def eval_fn(params, xs):
            return xs

        ev = jax.jit(eval_fn)
        """
    )
    assert _rules(fs) == []


def test_decorated_state_fn_flagged():
    fs = _lint(
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def step(state, n):
            return state
        """
    )
    assert _rules(fs) == [("missing-donate", 5)]


# ---------------------------------------------------------------------------
# no-model-closure-jit (serving modules only)
# ---------------------------------------------------------------------------


def _lint_serving(src: str):
    return lint_source(
        textwrap.dedent(src), path="midgpt_tpu/serving/probe.py"
    )


_CLOSURE_SRC = """
    import jax

    def build(model):
        def window_fn(pool, logits):
            return model(pool), logits

        return jax.jit(window_fn, donate_argnums=(0,))
    """


def test_model_closure_in_serving_flagged():
    fs = _lint_serving(_CLOSURE_SRC)
    assert ("no-model-closure-jit", 8) in _rules(fs)


def test_model_closure_outside_scoped_files_not_flagged():
    """The rule covers midgpt_tpu/serving/ plus the train-side jit
    sites (train.py / bench.py) — other modules may close over
    config-derived structures."""
    fs = lint_source(
        textwrap.dedent(_CLOSURE_SRC), path="midgpt_tpu/train_probe.py"
    )
    assert [(r, n) for r, n in _rules(fs) if r == "no-model-closure-jit"] == []


def test_model_closure_in_train_py_flagged():
    """train.py's jit sites are in scope: a train program closing over
    the model would constant-fold the params into the executable and
    break donation (the PR 6 serving bug class, train-side)."""
    fs = lint_source(textwrap.dedent(_CLOSURE_SRC), path="midgpt_tpu/train.py")
    assert ("no-model-closure-jit", 8) in _rules(fs)


def test_model_closure_in_bench_py_flagged():
    fs = lint_source(textwrap.dedent(_CLOSURE_SRC), path="bench.py")
    assert ("no-model-closure-jit", 8) in _rules(fs)


def test_unrolled_layer_loop_rule_stays_serving_scoped():
    """Extending the closure rule to train.py must NOT drag the
    layer-loop rule along — train.py's loop structure is gated by the
    train dispatch budget, not the AST lint."""
    src = """
        import jax

        def loss(layers, x):
            for layer in layers:
                x = attention(layer, x)
            return x
        """
    fs = lint_source(textwrap.dedent(src), path="midgpt_tpu/train.py")
    assert [(r, n) for r, n in _rules(fs) if r == "no-unrolled-layer-loop"] == []


def test_model_as_parameter_passes():
    fs = _lint_serving(
        """
        import jax

        def build():
            def window_fn(model, pool, logits):
                return model(pool), logits

            return jax.jit(window_fn, donate_argnums=(1,))
        """
    )
    assert _rules(fs) == []


def test_model_closure_lambda_flagged():
    fs = _lint_serving(
        """
        import jax

        def build(model, window_fn):
            return jax.jit(lambda pool: window_fn(model, pool))
        """
    )
    assert ("no-model-closure-jit", 5) in _rules(fs)


def test_model_closure_decorator_flagged():
    fs = _lint_serving(
        """
        import functools
        import jax

        def build(model):
            @functools.partial(jax.jit, donate_argnums=(0,))
            def window_fn(pool):
                return model(pool)

            return window_fn
        """
    )
    assert ("no-model-closure-jit", 6) in _rules(fs)


def test_model_closure_not_hidden_by_nested_local_binding():
    """A nested helper that binds its OWN local `model` must not mask a
    genuine capture by the jitted function (scope-aware free-variable
    analysis — a flat bound set would swallow the real finding)."""
    fs = _lint_serving(
        """
        import jax

        def build(model):
            def window_fn(pool):
                def helper(x):
                    model = x * 2
                    return model

                return helper(pool) + model.wte

            return jax.jit(window_fn)
        """
    )
    assert any(r == "no-model-closure-jit" for r, _ in _rules(fs))


def test_nested_def_model_parameter_not_flagged():
    """A nested def whose PARAMETER is named model binds it in its own
    scope — the jitted function captures nothing."""
    fs = _lint_serving(
        """
        import jax

        def build():
            def window_fn(pool):
                def helper(model):
                    return model + 1

                return helper(pool)

            return jax.jit(window_fn)
        """
    )
    assert _rules(fs) == []


def test_model_closure_waivable():
    fs = _lint_serving(
        """
        import jax

        def build(model):
            def warm_fn(pool):
                return model(pool)

            return jax.jit(warm_fn)  # shardlint: disable=no-model-closure-jit
        """
    )
    assert _rules(fs) == []
    assert any(
        f.rule == "no-model-closure-jit" and f.waived for f in fs
    )


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


def test_pragma_waives_named_rule():
    fs = _lint(
        """
        import jax

        def step(state, x):
            return state

        train = jax.jit(step)  # shardlint: disable=missing-donate
        """
    )
    assert _rules(fs) == []
    assert [(f.rule, f.waived) for f in fs] == [("missing-donate", True)]


def test_bare_pragma_waives_all():
    fs = _lint(
        """
        from jax.sharding import PartitionSpec as P

        spec = P("tenzor")  # shardlint: disable
        """
    )
    assert _rules(fs) == []


def test_pragma_on_other_line_does_not_waive():
    fs = _lint(
        """
        import jax
        # shardlint: disable=missing-donate

        def step(state, x):
            return state

        train = jax.jit(step)
        """
    )
    assert _rules(fs) == [("missing-donate", 8)]


# ---------------------------------------------------------------------------
# no-unrolled-layer-loop (serving modules only)
# ---------------------------------------------------------------------------


_LAYER_LOOP_SRC = """
    import jax

    def build(cfg):
        def window_fn(model, pool):
            h = pool
            for i in range(cfg.n_layer):
                h = model.block(h, i)
            return h

        return jax.jit(window_fn, donate_argnums=(1,))
    """


def test_unrolled_layer_loop_in_serving_flagged():
    fs = _lint_serving(_LAYER_LOOP_SRC)
    assert ("no-unrolled-layer-loop", 7) in _rules(fs)


def test_unrolled_layer_loop_outside_serving_not_flagged():
    """Scoped to midgpt_tpu/serving/: the models/ drivers keep their
    unrolled layer_scan="off" branch on purpose (it is the fold's
    bitwise reference, selected by the engine knob)."""
    fs = lint_source(
        textwrap.dedent(_LAYER_LOOP_SRC), path="midgpt_tpu/models/probe.py"
    )
    assert [
        (r, n) for r, n in _rules(fs) if r == "no-unrolled-layer-loop"
    ] == []


def test_unrolled_layer_loop_untraced_not_flagged():
    """A host-side loop over layers (checkpoint surgery, stats) is not
    a jitted program body — only traced roots are in scope."""
    fs = _lint_serving(
        """
        def describe(cfg, params):
            out = []
            for i in range(cfg.n_layer):
                out.append(params[i].shape)
            return out
        """
    )
    assert _rules(fs) == []


def test_unrolled_layer_loop_waivable():
    fs = _lint_serving(
        """
        import jax

        def build(cfg):
            def window_fn(model, pool):
                h = pool
                for i in range(cfg.n_layer):  # shardlint: disable=no-unrolled-layer-loop
                    h = model.block(h, i)
                return h

            return jax.jit(window_fn, donate_argnums=(1,))
        """
    )
    assert _rules(fs) == []  # _rules filters to unwaived findings


def test_non_layer_loop_in_serving_not_flagged():
    fs = _lint_serving(
        """
        import jax

        def build(cfg):
            def window_fn(model, pool):
                h = pool
                for i in range(4):
                    h = h + model.step(h)
                return h

            return jax.jit(window_fn, donate_argnums=(1,))
        """
    )
    assert _rules(fs) == []


# ---------------------------------------------------------------------------
# the shipped tree stays clean
# ---------------------------------------------------------------------------


def test_midgpt_tpu_tree_is_lint_clean():
    """The acceptance bar of the analysis PR, kept as an invariant:
    zero unwaived findings over the whole package. New waivers must be
    explicit inline pragmas, which show up in diffs."""
    pkg = pathlib.Path(midgpt_tpu.__file__).parent
    findings = unwaived(lint_paths([pkg]))
    assert findings == [], "\n".join(str(f) for f in findings)
