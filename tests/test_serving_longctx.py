"""Long-context serving (ISSUE 19): sequence-parallel prefill +
host-RAM cold-page spill — the landing gates asserted directly.

- **SP prefill bit-identity**: ``prefill_sp="on"`` shards the prefill
  chunk's query rows over the 'tensor' axis but runs the off-path
  arithmetic verbatim (the choreo prover's sp leg proves zero added
  arithmetic; these tests pin the streams). Greedy AND sampled streams
  are bitwise identical to ``prefill_sp="off"`` — and to the
  single-chip engine — across cache x chunk x spec x kv-quant x
  layer_scan at tp=2 (fast) and tp=4 (slow). Decode programs are
  untouched by construction (separate ``_PROGRAM_CACHE`` entries; the
  resolved sp value forks only the prefill-chunk key).
- **Spill bit-identity**: with ``spill="on"`` cold prefix pages move to
  host RAM instead of being reclaimed and fault back byte-exactly
  through the jitted page-write path, so pressured streams equal the
  ample-pool reference bit for bit — including eviction-under-pressure
  mid-spill (a bounded host budget forcing discards), COW against a
  spilled parent page, and a disaggregated handoff whose prefix chain
  is partially spilled on the prefill replica.
- **Accounting**: the allocator identity plus the extended spill ledger
  (resident-indexed and spilled node sets disjoint, spill store and
  index in bijection, spilled subtrees closed downward) re-check after
  EVERY scheduler step in spill mode.
- **No-wedge acceptance**: a pool smaller than a long request's chain
  plus its concurrent short traffic finishes everything — parking +
  spill absorb the pressure; nothing raises ``PoolOverloaded`` and the
  long prompt's chain survives (host-side) to serve a fault-back hit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import MeshConfig, ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.serving import ServingCluster, ServingEngine, pages_needed
from midgpt_tpu.serving.engine import _PROGRAM_CACHE

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _mesh(tp):
    return create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp),
        devices=jax.devices()[:tp],
    )


def _prompts(n, base_len=5, stride=3, seed0=100):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(seed0 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def _run(model, mesh, prompts, n_new, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    eng = ServingEngine(model, mesh=mesh, **kw)
    rids = [eng.submit(p, n_new, seed=i) for i, p in enumerate(prompts)]
    finished = eng.run()
    return [list(map(int, finished[r].tokens)) for r in rids], eng


def _check(eng):
    """Allocator identity + prefix-index structure + the spill ledger
    (store/index bijection, downward closure) in one call."""
    eng.alloc.check()
    if eng.index is not None:
        eng.index.check(eng.alloc, eng._spill_store)


def _force_spill(eng, k=None):
    """Push ``k`` coldest-eligible cached pages (all of them when None)
    out to the host store through the engine's own reservation path —
    the same code a pressured admit runs, just without needing filler
    traffic to generate the pressure."""
    assert eng._spill_store is not None
    target = (
        eng.alloc.num_pages if k is None else eng.alloc.free_pages + k
    )
    eng._try_reserve(target)


# ---------------------------------------------------------------------------
# sequence-parallel prefill: resolution + bit-identity
# ---------------------------------------------------------------------------


def test_sp_resolution_and_program_cache_fork(model):
    """"auto" turns on exactly when the mesh has a tensor axis; the
    RESOLVED value rides the prefill-chunk program-cache key (decode
    keys untouched), so on/off engines never share a compilation."""
    single = ServingEngine(model, slots=1, page_size=8, window=2)
    assert single.prefill_sp == "off"  # no axis to shard over
    tp_auto = ServingEngine(
        model, slots=1, page_size=8, window=2, mesh=_mesh(2)
    )
    assert tp_auto.prefill_sp == "on"
    tp_off = ServingEngine(
        model, slots=1, page_size=8, window=2, mesh=_mesh(2),
        prefill_sp="off",
    )
    assert tp_off.prefill_sp == "off"
    # run one tiny prompt through each resolved mode: the cache must
    # hold prefill_chunk entries for BOTH sp values (key slot 6), and
    # no decode/verify key carries an sp field at all
    for eng in (tp_auto, tp_off):
        eng.submit(_prompts(1)[0], 2, seed=0)
        eng.run()
    sps = {k[6] for k in _PROGRAM_CACHE if k[0] == "prefill_chunk"}
    assert {"on", "off"} <= sps
    assert all(
        k[0] in ("prefill_chunk", "decode_window", "verify_program")
        or "sp" not in str(k[0])
        for k in _PROGRAM_CACHE
    )


def test_sp_prefill_greedy_identity_tp2(model):
    """The tentpole gate, fast shape: long-ish chunked prompts, greedy —
    sp=on streams equal sp=off on the SAME tp=2 mesh AND the single-chip
    engine, bit for bit, with the prefix cache exercised."""
    prompts = _prompts(3, base_len=20, stride=6)
    kw = dict(page_size=8, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, prompts, 10, **kw)
    off, _ = _run(model, _mesh(2), prompts, 10, prefill_sp="off", **kw)
    on, eng = _run(model, _mesh(2), prompts, 10, prefill_sp="on", **kw)
    assert on == off == ref
    assert eng.prefill_sp == "on"


def test_sp_prefill_sampled_identity_tp2(model):
    """Sampled streams (temperature + top_k, per-request seeds): the
    sp=on engine draws the identical token sequence — sampling reads
    logits, and sp must not perturb a single bit of them."""
    prompts = _prompts(3, base_len=16, stride=5)
    kw = dict(
        page_size=8, prefill_chunk=8, temperature=0.8, top_k=16,
    )
    off, _ = _run(model, _mesh(2), prompts, 12, prefill_sp="off", **kw)
    on, _ = _run(model, _mesh(2), prompts, 12, prefill_sp="on", **kw)
    assert on == off


# ---------------------------------------------------------------------------
# host spill: bit-identity + fault-back + accounting
# ---------------------------------------------------------------------------


def test_spill_pressure_greedy_identity_and_faultback(model):
    """A pool too small for the trace's chains: cold pages spill
    instead of being reclaimed, streams stay bitwise the ample-pool
    reference, and resubmitting the prompts hits the HOST-side prefix
    (fault-back > 0) with the same streams again."""
    prompts = _prompts(4, base_len=22, stride=0, seed0=300)
    kw = dict(page_size=8, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, prompts, 12, **kw)
    got, eng = _run(
        model, None, prompts, 12, num_pages=8, spill="on", **kw
    )
    assert got == ref
    st = eng.stats()
    assert st["spilled_pages"] > 0, "pool pressure never materialized"
    _check(eng)
    # resubmit the same prompts on the SAME engine: matches walk onto
    # spilled nodes and fault back byte-exactly
    rids = [eng.submit(p, 12, seed=i) for i, p in enumerate(prompts)]
    fin = eng.run()
    again = [list(map(int, fin[r].tokens)) for r in rids]
    assert again == ref
    assert eng.stats()["spill_faultback_pages"] > 0
    _check(eng)


def test_spill_kv8_scale_planes_travel_with_payload(model):
    """int8 KV pool under spill: the per-(page, head) scale planes spill
    and fault back WITH their payloads — a stale scale on a revived
    page would be deterministic silent corruption, caught here as a
    stream mismatch."""
    prompts = _prompts(3, base_len=22, stride=0, seed0=400)
    kw = dict(
        page_size=8, prefill_chunk=8, kv_quant="int8", prefix_cache=True
    )
    ref, _ = _run(model, None, prompts, 10, **kw)
    got, eng = _run(
        model, None, prompts, 10, num_pages=7, spill="on", **kw
    )
    assert got == ref
    assert eng.stats()["spilled_pages"] > 0
    rids = [eng.submit(p, 10, seed=i) for i, p in enumerate(prompts)]
    fin = eng.run()
    assert [list(map(int, fin[r].tokens)) for r in rids] == ref
    assert eng.stats()["spill_faultback_pages"] > 0
    _check(eng)


def test_spill_sampled_identity(model):
    """Sampled spill streams: temperature > 0 with per-request seeds —
    pressure + spill + fault-back must not shift the sampled sequence
    by a single draw."""
    prompts = _prompts(3, base_len=22, stride=0, seed0=500)
    kw = dict(
        page_size=8, prefill_chunk=8, temperature=0.8, top_k=16,
        prefix_cache=True,
    )
    ref, _ = _run(model, None, prompts, 12, **kw)
    got, eng = _run(
        model, None, prompts, 12, num_pages=7, spill="on", **kw
    )
    assert got == ref
    assert eng.stats()["spilled_pages"] > 0
    _check(eng)


def test_spill_prefetch_batches_faultbacks_and_keeps_streams(
    model, monkeypatch
):
    """Prefetch-on-queue (ISSUE 20): the scheduler probes the wait-queue
    head's prompt each step and fault-backs its matched spilled nodes
    BEFORE admission in ONE batched import_pages call. Streams must stay
    bitwise identical to fault-on-match (imports are byte-exact either
    way) while the import dispatch count on the TTFT path DROPS — the
    per-node fault_back calls collapse into per-step batches."""
    import midgpt_tpu.serving.engine as engine_mod

    prompts = _prompts(4, base_len=22, stride=0, seed0=700)
    kw = dict(page_size=8, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, prompts, 12, **kw)
    real_import = engine_mod.import_pages
    calls = {}
    engines = {}
    for mode in ("off", "on"):
        counter = {"n": 0}

        def counting(pool, ids, *a, _c=counter, **k2):
            _c["n"] += 1
            return real_import(pool, ids, *a, **k2)

        monkeypatch.setattr(engine_mod, "import_pages", counting)
        got, eng = _run(
            model, None, prompts, 12, num_pages=8, spill="on",
            spill_prefetch=mode, **kw
        )
        assert got == ref
        assert eng.stats()["spilled_pages"] > 0
        assert eng.stats()["spill_resident_pages"] > 0
        _check(eng)
        # resubmit the same prompts: matches walk onto spilled nodes —
        # the import calls from HERE to stream completion are the
        # revival dispatches on the resubmits' TTFT path
        base = counter["n"]
        rids = [eng.submit(p, 12, seed=i) for i, p in enumerate(prompts)]
        fin = eng.run()
        assert [list(map(int, fin[r].tokens)) for r in rids] == ref
        _check(eng)
        calls[mode] = counter["n"] - base
        engines[mode] = eng
    st_on, st_off = engines["on"].stats(), engines["off"].stats()
    assert st_off["spill_prefetch_pages"] == 0
    assert st_on["spill_prefetch_pages"] > 0
    assert st_on["spill_faultback_pages"] > 0
    assert calls["on"] > 0 and calls["off"] > 0
    assert calls["on"] < calls["off"], (calls, st_on, st_off)


def test_eviction_under_pressure_mid_spill(model):
    """spill_budget_pages bounds host residency: past it the oldest
    spilled prefixes are discarded outright (true reclaim resumes, the
    degradation floor) — the engine keeps serving, streams stay
    bitwise, and the ledger stays consistent through the spill/discard
    churn."""
    prompts = _prompts(5, base_len=22, stride=0, seed0=600)
    kw = dict(page_size=8, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, prompts, 12, **kw)
    got, eng = _run(
        model, None, prompts, 12, num_pages=8, spill="on",
        spill_budget_pages=3, **kw
    )
    assert got == ref
    st = eng.stats()
    assert st["spilled_pages"] > 0
    assert st["spill_discards"] > 0, "budget never forced a discard"
    assert st["spill_resident_pages"] <= 3
    _check(eng)


def test_spill_store_nbytes_counter_and_protected_discard():
    """HostSpillStore.nbytes is a running counter (put/pop — the
    telemetry gauge must not walk every payload per sample), and
    PrefixIndex.discard_spilled_oldest honors the protect set an
    in-flight fault-back passes."""
    from midgpt_tpu.serving.paged import HostSpillStore, PrefixIndex

    store = HostSpillStore(budget_pages=1)
    x = np.arange(8, dtype=np.float32)
    store.put(-2, (x, x, None, None))
    store.put(-3, (x, x, x, x))
    assert store.nbytes == 6 * x.nbytes
    store.pop(-2)
    assert store.nbytes == 4 * x.nbytes
    store.pop(-3)
    assert store.nbytes == 0

    idx = PrefixIndex(2)
    p0 = idx.register(PrefixIndex._ROOT, (1, 2), 0)
    p1 = idx.register(p0, (3, 4), 1)
    idx.touch_cold(p1)
    idx.touch_cold(p0)
    v1 = idx.spill(p1)  # deepest-first: the tail spills oldest
    v0 = idx.spill(p0)
    # whole chain protected: nothing is discardable
    assert idx.discard_spilled_oldest({v0, v1}) is None
    # tail protected only: v0 still has a (spilled) child -> still None
    assert idx.discard_spilled_oldest({v1}) is None
    # unprotected: oldest childless (the tail) goes first
    assert idx.discard_spilled_oldest() == v1
    assert idx.discard_spilled_oldest({v1}) == v0


def test_budget_discard_protects_inflight_faultback_chain(model):
    """Regression: a fault-back's own reservation can spill victims
    past spill_budget_pages, and the budget-discard pass used to drop
    the oldest CHILDLESS spilled node — deepest-first spill makes that
    exactly the tail of the chain being materialized. The chain's vids
    are now protected for the duration (host residency transiently
    overshoots, then drains as the fault-back pops the payloads);
    before, the in-flight vid was discarded out from under _fault_back
    (KeyError at the store pop), or a later chain node silently
    survived as a negative virtual id in the slot's block table."""
    ps = 8
    a = _prompts(1, base_len=20, stride=0, seed0=800)[0]  # 2-node chain
    b = _prompts(1, base_len=28, stride=0, seed0=801)[0]  # fills the pool
    kw = dict(page_size=ps, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, [a], 3, **kw)
    eng = ServingEngine(
        model, slots=2, page_size=ps, window=4,
        cache_dtype=jnp.float32, prefill_chunk=8, spill="on",
        spill_budget_pages=2, num_pages=4,
    )
    r1 = eng.submit(a, 3, seed=0)
    assert list(map(int, eng.run()[r1].tokens)) == ref[0]
    _force_spill(eng)  # a's 2-node chain -> host, store AT budget
    assert len(eng._spill_store) == 2
    r2 = eng.submit(b, 3, seed=1)
    eng.run()  # b's cold chain occupies all but one HBM page
    assert eng.alloc.free_pages == 1
    # the host budget tightens below the spilled chain between
    # admissions: the next discard pass runs with a's nodes oldest AND
    # the pool pressured enough that a's own fault-back must spill b
    eng._spill_store.budget_pages = 1
    r3 = eng.submit(a, 3, seed=0)
    fin = eng.run()
    assert list(map(int, fin[r3].tokens)) == ref[0]
    st = eng.stats()
    assert st["spill_faultback_pages"] >= 2  # both chain nodes revived
    assert st["spill_discards"] > 0  # budget pressed mid-admission
    _check(eng)


def test_cow_on_spilled_parent_page(model):
    """A new request sharing a PARTIAL page with a spilled chain: the
    COW source page faults back from host before it is copied. Chain
    [p0, p1, p2] spills deepest-first; prompt B = A's first 12 tokens
    matches p0 fully and extends 4 tokens INTO p1 (spilled) -> the COW
    candidate is a virtual node, faulted back then copied — bitwise
    the no-spill run."""
    ps = 8
    a = _prompts(1, base_len=2 * ps, stride=0, seed0=700)[0]  # 2 pages
    b = a[: ps + 4]  # pure prefix ending mid-page-1: the COW shape
    # reference: same two requests, ample pool, no spill
    ref, _ = _run(
        model, None, [a, b], 8, page_size=ps, prefill_chunk=8,
        prefix_cache=True,
    )
    eng = ServingEngine(
        model, slots=2, page_size=ps, window=4,
        cache_dtype=jnp.float32, prefill_chunk=8, spill="on",
    )
    r1 = eng.submit(a, 8, seed=0)
    fin = eng.run()
    got_a = list(map(int, fin[r1].tokens))
    # a's chain is cold: spill it ENTIRELY so the match-walk must fault
    # the COW source back from the host store
    _force_spill(eng)
    assert eng.stats()["spilled_pages"] >= 2
    assert eng.index.coldest_leaf() is None  # nothing resident-cold left
    _check(eng)
    r2 = eng.submit(b, 8, seed=1)
    fin = eng.run()
    got_b = list(map(int, fin[r2].tokens))
    assert [got_a, got_b] == ref
    assert eng.stats()["spill_faultback_pages"] >= 2  # p0 + the COW src
    _check(eng)


def test_spill_invariants_property_loop(model):
    """Property-style: a busy shared-prefix trace in spill mode with
    real pressure — after EVERY scheduler step the allocator identity
    holds, the index/store ledger agrees (disjoint resident/spilled
    sets, downward closure — index.check with the store), LRU holds
    only refcount-0 resident pages, and writer pages have exactly one
    owner."""
    sys_prompt = _prompts(1, base_len=16, seed0=800)[0]
    tails = _prompts(6, base_len=2, stride=1, seed0=810)
    prompts = [np.concatenate([sys_prompt, t]) for t in tails]
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=10, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        prefill_chunk=8, spill="on",
    )
    rids = [eng.submit(p, 10, seed=i) for i, p in enumerate(prompts)]
    steps = 0
    while (eng.queue or eng._active_slots()) and steps < 500:
        eng.step()
        steps += 1
        _check(eng)
        # every indexed node is resident-or-spilled, never both; the
        # spilled count and the host store agree
        spilled = {n for n in eng.index._meta if eng.index.is_spilled(n)}
        assert len(spilled) == len(eng._spill_store)
        for s in eng._active_slots():
            for pg in eng.slot_pages[s]:
                assert pg >= 0 and not eng.index.is_spilled(pg)
                if pg in eng.index:
                    continue
                assert eng.alloc.refcount(pg) == 1, (
                    f"writer page {pg} shared"
                )
    assert steps < 500, "engine did not drain"
    assert eng.alloc.held_pages == 0
    assert (
        eng.alloc.free_pages + eng.alloc.cached_pages
        == eng.alloc.num_pages
    )
    for r in rids:
        assert len(eng.finished[r].tokens) == 10
    assert eng.stats()["spilled_pages"] > 0, "trace never pressured"


# ---------------------------------------------------------------------------
# composition: sp + spill, disagg handoff, the no-wedge acceptance gate
# ---------------------------------------------------------------------------


def test_sp_and_spill_compose_tp2(model):
    """Both tentpole halves at once: tp=2 SP prefill over a pool small
    enough to spill — streams bitwise the single-chip ample-pool
    engine."""
    prompts = _prompts(3, base_len=22, stride=0, seed0=900)
    kw = dict(page_size=8, prefill_chunk=8, prefix_cache=True)
    ref, _ = _run(model, None, prompts, 10, **kw)
    got, eng = _run(
        model, _mesh(2), prompts, 10, prefill_sp="on", spill="on",
        num_pages=7, **kw
    )
    assert got == ref
    assert eng.prefill_sp == "on"
    assert eng.stats()["spilled_pages"] > 0
    _check(eng)


def test_disagg_handoff_of_partially_spilled_chain(model):
    """Disaggregated pools with spill on the prefill replica: turn 1
    hands off and its prompt chain retires cold on the prefill engine;
    we spill PART of that chain (deepest-first, so the spilled nodes
    are a suffix); turn 2 (prompt + turn-1 output + new tokens) prefix-
    matches the partially-spilled chain, faults the suffix back, and
    hands off — bitwise the monolithic engine serving the same two
    turns."""
    kw = dict(
        slots=2, page_size=8, window=4, cache_dtype=jnp.float32,
        prefill_chunk=8, prefix_cache=True, spill="on",
    )
    a = _prompts(1, base_len=26, stride=0, seed0=1000)[0]
    # monolithic reference, turn by turn
    mono = ServingEngine(model, **kw)
    r1 = mono.submit(a, 8, seed=0)
    ref1 = list(map(int, mono.run()[r1].tokens))
    b = np.concatenate(
        [a, np.asarray(ref1, np.int32),
         _prompts(1, base_len=5, stride=0, seed0=1001)[0]]
    )
    r2 = mono.submit(b, 8, seed=1)
    ref2 = list(map(int, mono.run()[r2].tokens))

    cl = ServingCluster(
        model, prefill_replicas=1, decode_replicas=1, **kw
    )
    rid1 = cl.submit(a, 8, seed=0)
    while cl.has_work:
        cl.step()
        for i in cl._alive():
            _check(cl.engines[i])
    cl._harvest()
    assert list(map(int, cl.finished[rid1].tokens)) == ref1
    pre = next(e for e in cl.engines if e.role == "prefill")
    # spill a strict subset of a's prompt chain (the deepest pages)
    chain_pages = pre.alloc.cached_pages
    assert chain_pages >= 3, "prefill replica retained no chain"
    _force_spill(pre, 2)
    st = pre.stats()
    assert st["spilled_pages"] == 2
    assert 0 < st["spill_resident_pages"] < chain_pages
    _check(pre)
    rid2 = cl.submit(b, 8, seed=1)
    while cl.has_work:
        cl.step()
        for i in cl._alive():
            _check(cl.engines[i])
    cl._harvest()
    assert list(map(int, cl.finished[rid2].tokens)) == ref2
    assert pre.stats()["spill_faultback_pages"] > 0


def test_long_prompt_completes_in_undersized_pool_no_wedge(model):
    """The acceptance gate: the pool is smaller than the long request's
    chain plus its concurrent short traffic (8 pages vs a 7-page
    lifetime + 2 pages per short) — parking + spill absorb the
    pressure, every request finishes bitwise-correct, nothing raises
    PoolOverloaded, and the long chain survives host-side to serve a
    fault-back hit afterwards."""
    ps = 8
    long_p = _prompts(1, base_len=40, stride=0, seed0=1100)[0]
    shorts = _prompts(4, base_len=6, stride=0, seed0=1110)
    lifetime = pages_needed(len(long_p) + 16, ps)
    assert lifetime == 7
    # ample-pool references
    ref_long, _ = _run(
        model, None, [long_p], 16, page_size=ps, prefill_chunk=8
    )
    ref_short, _ = _run(
        model, None, shorts, 8, page_size=ps, prefill_chunk=8
    )
    eng = ServingEngine(
        model, slots=2, page_size=ps, num_pages=8, window=4,
        cache_dtype=jnp.float32, prefill_chunk=8, prefix_cache=True,
        spill="on",
    )
    assert eng.alloc.num_pages < lifetime + pages_needed(6 + 8, ps)
    rl = eng.submit(long_p, 16, seed=0)
    rs = [eng.submit(p, 8, seed=1 + i) for i, p in enumerate(shorts)]
    steps = 0
    while (eng.queue or eng._active_slots()) and steps < 600:
        eng.step()  # PoolOverloaded here would fail the test outright
        steps += 1
        _check(eng)
    assert steps < 600, "engine wedged under long+short pressure"
    fin = eng.finished
    assert list(map(int, fin[rl].tokens)) == ref_long[0]
    assert [list(map(int, fin[r].tokens)) for r in rs] == ref_short
    st = eng.stats()
    assert st["spilled_pages"] > 0, "undersized pool never spilled"
    assert st["deferred_submits"] == 0 and st["shed_requests"] == 0
    # the long chain is still matchable (host or resident): resubmit
    # and require a fault-back hit with the identical stream
    r2 = eng.submit(long_p, 16, seed=0)
    fin = eng.run()
    assert list(map(int, fin[r2].tokens)) == ref_long[0]
    assert st["spill_faultback_pages"] <= eng.stats()[
        "spill_faultback_pages"
    ]
    _check(eng)


# ---------------------------------------------------------------------------
# slow tier: the full identity matrix (CI serving-longctx job)
# ---------------------------------------------------------------------------

MATRIX_SLOW = [
    pytest.param(True, None, 0, None, "off", id="cache"),
    pytest.param(False, 8, 0, None, "off", id="chunked-nocache"),
    pytest.param(True, 8, 3, None, "off", id="chunked-spec"),
    pytest.param(True, 8, 0, "int8", "off", id="chunked-kv8"),
    pytest.param(True, 8, 3, "int8", "on", id="chunked-spec-kv8-scan"),
    pytest.param(True, 8, 0, None, "on", id="chunked-scan"),
]


@pytest.mark.slow
@pytest.mark.parametrize("cache,chunk,spec,kvq,ls", MATRIX_SLOW)
def test_sp_identity_matrix_tp2_slow(model, cache, chunk, spec, kvq, ls):
    prompts = _prompts(3, base_len=18, stride=4, seed0=1200)
    kw = dict(
        page_size=8, prefix_cache=cache, prefill_chunk=chunk,
        speculate=spec, kv_quant=kvq, layer_scan=ls,
    )
    off, _ = _run(model, _mesh(2), prompts, 10, prefill_sp="off", **kw)
    on, _ = _run(model, _mesh(2), prompts, 10, prefill_sp="on", **kw)
    assert on == off


@pytest.mark.slow
@pytest.mark.parametrize(
    "temperature", [0.0, 0.8], ids=["greedy", "sampled"]
)
def test_sp_identity_tp4_slow(model, temperature):
    prompts = _prompts(3, base_len=18, stride=4, seed0=1300)
    kw = dict(
        page_size=8, prefill_chunk=8, temperature=temperature,
        top_k=16 if temperature else None,
    )
    ref, _ = _run(model, None, prompts, 10, **kw)
    on, _ = _run(model, _mesh(4), prompts, 10, prefill_sp="on", **kw)
    assert on == ref


@pytest.mark.slow
@pytest.mark.parametrize(
    "spec,kvq,ls",
    [
        pytest.param(0, None, "off", id="plain"),
        pytest.param(3, None, "off", id="spec"),
        pytest.param(0, "int8", "on", id="kv8-scan"),
        pytest.param(3, "int8", "off", id="spec-kv8"),
    ],
)
def test_spill_identity_matrix_slow(model, spec, kvq, ls):
    prompts = _prompts(4, base_len=22, stride=0, seed0=1400)
    kw = dict(
        page_size=8, prefill_chunk=8, prefix_cache=True,
        speculate=spec, kv_quant=kvq, layer_scan=ls,
    )
    ref, _ = _run(model, None, prompts, 12, **kw)
    got, eng = _run(
        model, None, prompts, 12, num_pages=8, spill="on", **kw
    )
    assert got == ref
    assert eng.stats()["spilled_pages"] > 0
    _check(eng)
