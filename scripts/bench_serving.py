"""Serving bench: continuous-batching throughput under a Poisson request mix.

Drives midgpt_tpu.serving.ServingEngine with seeded Poisson arrivals
(random prompt/generation lengths), measures end-to-end on the real
clock, and emits ONE JSON record:

  serve_tok_s            generated tokens/s over the whole trace
  serve_ttft_p50_ms      time-to-first-token, median (arrival -> first token)
  serve_ttft_p99_ms      ... and p99
  serve_slot_occupancy   mean fraction of decode slots busy per window
  serve_decode_dispatches / serve_prefill_dispatches
  serve_tokens_per_dispatch   steady-state K * slots when saturated
  serve_prefix_hit_rate  prompt tokens served from the prefix cache
  serve_prefill_tokens_saved / serve_prefill_tokens_computed
  serve_cow_copies       copy-on-write page duplications
  serve_spec_acceptance_rate  drafted tokens the verify program accepted
                         (argmax agreement at temperature 0, rejection
                         sampling at temperature > 0)
  serve_verify_dispatches     speculative verify dispatches
  serve_quant            int8 quantized weight path on/off
  serve_peak_hbm_bytes   device peak HBM after the trace (null on CPU)
  serve_tbt_p50_ms / serve_tbt_p99_ms   per-token time-between-tokens at
                         the harvest cadence (telemetry-derived; tokens
                         land in fused K-token windows, so p50 collapses
                         toward 0 as K grows and p99 shows the window
                         wall time — serving.telemetry docstring)
  serve_queue_delay_p50_ms / _p99_ms    submit -> first admission
  serve_timeline_files   Perfetto-loadable Chrome trace timelines +
                         per-request derived metrics + the metrics
                         registry snapshot (--timeline_dir)
  serve_flight_dumps     dead-replica flight-recorder artifacts from
                         chaos runs; watchdog rows carry their dumps
                         in-band under "flight_recorder"
  serve_bytes_per_token_static  the analysis/traffic.py static HBM
                         decomposition (weights + live KV + logits per
                         decode step, per chip under --tp) at the
                         trace's mean live context — the roofline the
                         measured serve_tok_s is compared against, and
                         the generator of PERF.md's floor table
  serve_hbm_floor_ms_static     its ms/step floor at 800 GB/s

The quantized weight path (--quant on) converts the model to the int8
per-channel pytree (midgpt_tpu.quant) before the engine compiles its
programs: the weight stream every decode step pays halves (bf16 -> int8
bytes), which PERF.md r5's roofline puts at ~0.31 ms of the 0.43 ms
124M B=8 floor — run --quant off/on on the same trace to ladder it.

Self-speculative decoding (--spec on): every decode dispatch drafts up
to --spec_len tokens per request by n-gram lookup over the request's
own history and verifies them in one dispatch —
serve_tokens_per_dispatch is the headline (1 + E[accepted] tokens per
launch vs exactly 1 for --spec off at --window 1). At --temperature 0
acceptance is argmax agreement; at --temperature > 0 it is rejection
sampling against the decode sampler's own distribution (same token
distribution, same per-request key-derivation determinism — the
sampled-chat leg the speedup was previously locked out of), and
serve_spec_acceptance_rate reports the measured accept fraction either
way. Pair it with --repetitive, which tiles each prompt from a short
random pattern (the self-repeating traffic shape prompt-lookup drafting
exists for); random incompressible prompts keep acceptance (and the
win) near zero.

A shared-system-prompt mix (--sys_prompt_len N) prepends one fixed
N-token prefix to --sys_prompt_frac of all requests — the dominant
shape of production traffic (system prompts / few-shot templates) and
what the prefix cache exists for; run it with --prefix_cache on/off to
ladder the win. --prefill_chunk C prefills Sarathi-style in C-token
chunks interleaved with decode (bounds TTFT under long prompts).

Trace replay (--trace poisson|bursty|diurnal, serving.frontdoor): the
goodput-under-SLO harness — the metric the Gemma-on-TPU serving paper
(PAPERS.md) actually compares systems on. Seed-pinned arrival shapes
(memoryless / burst-arrival / rate-swept "diurnal"), long-tail
lognormal prompt lengths, shared-prefix TENANT mixes (--tenants K
zipf-assigned system prompts of --sys_prompt_len tokens), per-request
priorities (--priority_levels), per-request e2e deadlines (--slo_ms
[+ --slo_per_token_ms x budget]), and client cancellations
(--cancel_frac, after a seeded number of streamed tokens). The trace
drives the ASYNC front door (AsyncFrontDoor token streams over the
engine/cluster — so it composes with --fault_plan, --dp_replicas, and
--timeline_dir unchanged) and the record gains:

  serve_goodput_slo_tok_s   tokens from DEADLINE-MET requests only / wall
  serve_deadline_met / serve_deadline_missed   finished in/after SLO
  serve_deadline_shed       shed BEFORE dispatch (queued/parked expiry)
  serve_cancelled           client-cancelled streams (slot reclaimed,
                            pages retired cold)

Deadline-expired requests shed pre-dispatch by the engine's priority/
aging admission policy; tokens a late request still produced count in
serve_tok_s (work done) but not in goodput-under-SLO (work banked).

Chaos runs (--fault_plan "2:transient@0;4:crash@0", serving.faults spec
grammar) drive the trace through a ServingCluster with scripted,
deterministic fault injection: replica crashes/wedges/transient errors
recover via health-tracked failover (bit-identical streams — the chaos
suite's landing gate), and the record gains "status" plus recovery and
goodput-under-faults metrics (serve_goodput_tok_s counts only FINISHED
requests' tokens; serve_recovery_s is first-replica-death -> drain).
A whole-trace watchdog (--deadline_s) turns a wedged relay into a
structured {"status": "watchdog"} row instead of an opaque hang — so
BENCH_r*.json trajectories distinguish hardware wedges from regressions
(the r4/r5 lesson).

The decode-dispatch arithmetic is the point (PERF.md): the fixed-batch
sampler launches one XLA dispatch per generated token; the engine fuses K
whole-model steps per launch, so the dispatch count is ~tokens/(K*slots)
plus one prefill per admission. Random-init weights — throughput only.

    python scripts/bench_serving.py                 # 124M shape on device
    python scripts/bench_serving.py --preset tiny   # CPU sanity run
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=("124m", "tiny"), default="124m")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--rate", type=float, default=16.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--window", type=int, default=8,
                    help="decode steps fused per dispatch (K)")
    ap.add_argument("--page_size", type=int, default=16)
    ap.add_argument("--min_prompt", type=int, default=32)
    ap.add_argument("--max_prompt", type=int, default=256)
    ap.add_argument("--min_new", type=int, default=32)
    ap.add_argument("--max_new", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--prefix_cache", choices=("on", "off"), default="on")
    ap.add_argument("--prefill_chunk", type=int, default=0,
                    help="chunked-prefill chunk size in tokens "
                    "(0 = monolithic prefill)")
    ap.add_argument("--prompt_len", type=int, default=0,
                    help="long-document preset: pin EVERY prompt to "
                    "exactly this many tokens (overriding --min_prompt/"
                    "--max_prompt) and widen the model's block_size to "
                    "fit prompt_len + sys_prompt + max_new — the 100k-"
                    "token serving shape the sequence-parallel prefill "
                    "and host-spill rungs measure (0 = off)")
    ap.add_argument("--prefill_sp", choices=("auto", "on", "off"),
                    default="auto",
                    help="sequence-parallel prefill (serving.engine "
                    "prefill_sp): shard each prefill chunk's query rows "
                    "across the 'tensor' mesh axis so a chunk's "
                    "attention+MLP compute drops to 1/tp per chip — "
                    "streams stay bitwise identical to 'off' (choreo-"
                    "prover gated). 'auto' = on when tp > 1; decode is "
                    "untouched either way")
    ap.add_argument("--spill", choices=("on", "off"), default="off",
                    help="host-RAM cold-page spill (serving.paged "
                    "HostSpillStore): under pool pressure, refcount-0 "
                    "cached pages (+ int8 scale planes) move to host "
                    "RAM in LRU order instead of being discarded, and "
                    "fault back byte-exactly on a prefix hit — the "
                    "prefix cache's capacity extends past HBM. Requires "
                    "--prefix_cache on")
    ap.add_argument("--spill_budget_pages", type=int, default=0,
                    help="cap on host-resident spilled pages (0 = "
                    "unbounded): past it the oldest childless spilled "
                    "pages are discarded, never the pool wedged")
    ap.add_argument("--num_pages", type=int, default=0,
                    help="KV pool size in pages (0 = slots * pages-per-"
                    "slot default): the spill-pressure rungs size the "
                    "pool BELOW the trace's working set so cold pages "
                    "actually spill")
    ap.add_argument("--sys_prompt_len", type=int, default=0,
                    help="length of a shared system prompt prepended to "
                    "--sys_prompt_frac of requests (0 = independent "
                    "prompts)")
    ap.add_argument("--sys_prompt_frac", type=float, default=1.0)
    ap.add_argument("--spec", choices=("on", "off"), default="off",
                    help="self-speculative decoding (n-gram drafting + "
                    "single-dispatch verification): argmax acceptance "
                    "at --temperature 0, rejection-sampling acceptance "
                    "at --temperature > 0 — same stream contract "
                    "either way")
    ap.add_argument("--spec_len", type=int, default=8,
                    help="max draft tokens per verify dispatch (--spec on)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature (0 = greedy, the "
                    "dispatch-arithmetic default): > 0 samples every "
                    "emitted token from the temperature/top_k-shaped "
                    "distribution with per-request (seed, token-index) "
                    "key derivation, and composes with --spec on via "
                    "rejection-sampling verification — the sampled-chat "
                    "traffic shape")
    ap.add_argument("--top_k", type=int, default=None,
                    help="top-k sampling cutoff (--temperature > 0)")
    ap.add_argument("--repetitive", action="store_true",
                    help="tile each prompt from a short random pattern — "
                    "the self-repeating workload n-gram drafting targets")
    ap.add_argument("--kv_quant", choices=("on", "off"), default="off",
                    help="int8-quantized paged KV pool (serving.paged): "
                    "page payloads store int8 with one f32 po2 scale "
                    "per (page, KV-head) plane, halving the K+V HBM "
                    "stream every decode step pays — the largest "
                    "remaining stream after --quant halves the weights "
                    "(PERF.md floor decomposition)")
    ap.add_argument("--paged_kernel", choices=("auto", "pallas", "xla"),
                    default="auto",
                    help="paged-attention backend: 'pallas' walks each "
                    "slot's block table IN-KERNEL over its ragged "
                    "length (ops.paged_attn — pages stream from HBM "
                    "once, no gathered [S, Pmax*PS, ...] intermediate), "
                    "'xla' keeps the gather path, 'auto' = pallas on "
                    "TPU when the VMEM assembly fits")
    ap.add_argument("--layer_scan", choices=("on", "off"), default="off",
                    help="fold each program's per-layer loop into one "
                    "lax.scan (models.gpt layer_scan, ROADMAP item 1): "
                    "one inlined layer body per program instead of L, "
                    "shrinking the per-dispatch launch structure the "
                    "decode residual over the HBM floor is made of — "
                    "bitwise the unrolled program (gated by the "
                    "analysis.fusion prover + dispatch budgets); run "
                    "on/off on the same trace to ladder the win")
    ap.add_argument("--quant", choices=("on", "off"), default="off",
                    help="serve the int8 per-channel quantized weight "
                    "path (midgpt_tpu.quant): dequant fused into each "
                    "matmul, halving the per-token weight HBM stream — "
                    "visible as both serve_tok_s (latency) and "
                    "serve_peak_hbm_bytes (memory)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree per engine replica: "
                    "weights column/row-parallel, KV pool sharded by "
                    "whole KV heads, vocab-sharded logits — the "
                    "per-chip weight/KV stream drops to 1/tp at the "
                    "cost of 2 activation-row psums per layer (PERF.md "
                    "arithmetic); needs tp*dp_replicas devices")
    ap.add_argument("--dp_replicas", type=int, default=1,
                    help="shared-nothing data-parallel engine replicas "
                    "under least-loaded admission "
                    "(midgpt_tpu.serving.ServingCluster); each replica "
                    "owns tp devices, its own page pool and prefix "
                    "cache — throughput scales, nothing is shared")
    ap.add_argument("--disagg", default=None, metavar="P+D",
                    help="disaggregated prefill/decode pools: 'P+D' runs "
                    "P prefill-class replicas (chunked prefill to "
                    "completion, then page handoff) and D decode-class "
                    "replicas (midgpt_tpu.serving.ServingCluster("
                    "prefill_replicas=, decode_replicas=)); streams stay "
                    "bit-identical to the monolithic engine, the record "
                    "gains handoff counters and a per-class TTFT split. "
                    "Mutually exclusive with --dp_replicas > 1")
    ap.add_argument("--affinity", choices=("on", "off"), default="off",
                    help="prefix-affinity admission: route each request "
                    "to the replica whose resident prefix cache overlaps "
                    "its prompt longest (load-imbalance capped, "
                    "least-loaded fallback) — the zipf --tenants trace "
                    "is the workload where this strictly beats blind "
                    "least-loaded on serve_prefix_hit_rate")
    ap.add_argument("--fault_plan", default=None,
                    help="scripted chaos (serving.faults spec grammar, "
                    "e.g. '2:transient@0;4:crash@0'): deterministic "
                    "fault injection keyed to scheduler steps, driven "
                    "through a ServingCluster so crash/wedge/transient "
                    "recover via failover — the record gains recovery + "
                    "goodput-under-faults metrics")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None,
                    help="cluster wall-clock dispatch watchdog (the "
                    "wedged-relay case): a replica step exceeding this "
                    "is abandoned and its backlog fails over")
    ap.add_argument("--max_retries", type=int, default=3,
                    help="capped-exponential-backoff retries for "
                    "transient dispatch errors before failover")
    ap.add_argument("--backoff_s", type=float, default=0.05)
    ap.add_argument("--trace", choices=("off", "poisson", "bursty",
                                        "diurnal"), default="off",
                    help="trace-replay mode (serving.frontdoor): drive "
                    "the request mix through the ASYNC front door with "
                    "the named seed-pinned arrival shape — 'poisson' "
                    "memoryless at --rate, 'bursty' Poisson burst "
                    "epochs of --burst_size back-to-back arrivals, "
                    "'diurnal' a sinusoidal rate sweep over the trace "
                    "— plus long-tail lognormal prompt lengths; emits "
                    "goodput-under-SLO next to the raw tok/s")
    ap.add_argument("--burst_size", type=int, default=8,
                    help="arrivals per burst epoch (--trace bursty)")
    ap.add_argument("--slo_ms", type=float, default=0.0,
                    help="per-request end-to-end SLO in ms from "
                    "arrival (0 = no deadline): requests finishing "
                    "late count deadline-missed, requests still "
                    "queued/parked past it are SHED before dispatch "
                    "(typed outcome), and serve_goodput_slo_tok_s "
                    "counts deadline-met tokens only")
    ap.add_argument("--slo_per_token_ms", type=float, default=0.0,
                    help="extra SLO budget per requested token "
                    "(deadline = arrival + slo_ms + slo_per_token_ms "
                    "* max_new)")
    ap.add_argument("--priority_levels", type=int, default=1,
                    help="uniform seeded per-request priority in "
                    "[0, L): the engine's aging admission dispatches "
                    "high first, starvation-proof (1 = FIFO)")
    ap.add_argument("--cancel_frac", type=float, default=0.0,
                    help="fraction of requests whose client cancels "
                    "the stream after a seeded number of tokens — "
                    "exercises cancellation-safe teardown under load")
    ap.add_argument("--tenants", type=int, default=0,
                    help="shared-prefix tenant mix (--trace modes): K "
                    "distinct --sys_prompt_len-token system prompts, "
                    "zipf-ish assigned, replacing the single shared "
                    "prefix of --sys_prompt_frac")
    ap.add_argument("--max_queue", type=int, default=0,
                    help="bounded engine wait queue (0 = unbounded): "
                    "with the front door, defer outcomes become "
                    "awaitable backpressure on the submitting client")
    ap.add_argument("--telemetry", choices=("on", "off"), default="on",
                    help="per-request lifecycle tracing "
                    "(serving.telemetry): on gives the record TBT and "
                    "queue-delay percentiles and arms the flight "
                    "recorder / timeline export. Tracing never touches "
                    "the compiled programs (greedy streams are bitwise "
                    "identical on/off; measured overhead is the "
                    "host-side scheduler only — PERF.md) — 'off' exists "
                    "to ladder exactly that claim on hardware")
    ap.add_argument("--metrics_out", default=None,
                    help="write the metrics-registry snapshot (engine "
                    "or cluster + per-replica) in Prometheus text "
                    "exposition format to this path "
                    "(midgpt_tpu.telemetry.prometheus_text) — the "
                    "pull-scrape view of metrics_snapshot.json")
    ap.add_argument("--timeline_dir", default=None,
                    help="write per-replica Chrome trace-event timelines "
                    "(openable in Perfetto), the per-request derived "
                    "metrics, and the metrics-registry snapshot under "
                    "this directory; also where dead-replica "
                    "flight-recorder dumps land on chaos runs "
                    "(default: flight dumps go next to --out)")
    ap.add_argument("--deadline_s", type=float, default=900.0,
                    help="whole-trace watchdog: if the trace has not "
                    "drained by then, emit a structured "
                    '{"status": "watchdog"} row and exit — BENCH_r*.json '
                    "then records a hardware wedge as a wedge, not an "
                    "opaque error (the r4/r5 lesson)")
    ap.add_argument("--out", default=None,
                    help="output JSON path (default "
                    "artifacts/bench_serving.json; the r6 queue's K-ladder "
                    "passes distinct paths so records don't overwrite)")
    from midgpt_tpu.utils.platform_pin import add_platform_arg, apply_platform

    add_platform_arg(ap)
    args = ap.parse_args()
    apply_platform(args.platform)

    # whole-RUN watchdog, armed BEFORE backend init: the r4/r5 wedges
    # happened in the compile/init phase, so a deadline that only covers
    # the timed trace would still hang opaquely there. A wedge at any
    # phase must yield a STRUCTURED row ({"status": "watchdog", "phase":
    # ...}), not an opaque hang/error — BENCH trajectories then separate
    # hardware wedges from regressions. Daemon thread + os._exit like
    # bench.py's watchdogs.
    import threading

    shape = (
        f"{args.preset} S={args.slots} K={args.window} "
        f"page={args.page_size} cache={args.prefix_cache} "
        f"chunk={args.prefill_chunk or 'mono'} "
        f"sys={args.sys_prompt_len} "
        f"spec={args.spec_len if args.spec == 'on' else 'off'}"
        f"{f' T={args.temperature:g}' if args.temperature else ''}"
        f"{f' topk={args.top_k}' if args.top_k else ''}"
        f"{' rep' if args.repetitive else ''}"
        f" quant={args.quant} kv_quant={args.kv_quant}"
        f" kernel={args.paged_kernel} ls={args.layer_scan}"
        f" tp={args.tp} dp={args.dp_replicas}"
        f"{f' plen={args.prompt_len}' if args.prompt_len else ''}"
        f" sp={args.prefill_sp}"
        f"{' spill' if args.spill == 'on' else ''}"
        f"{f' pool={args.num_pages}' if args.num_pages else ''}"
        f"{f' disagg={args.disagg}' if args.disagg else ''}"
        f"{' affinity' if args.affinity == 'on' else ''}"
        f"{' faults=' + args.fault_plan if args.fault_plan else ''}"
        f"{' trace=' + args.trace if args.trace != 'off' else ''}"
        f"{f' slo={args.slo_ms:g}ms' if args.slo_ms else ''}"
        f"{f' prio={args.priority_levels}' if args.priority_levels > 1 else ''}"
        f"{f' cancel={args.cancel_frac:g}' if args.cancel_frac else ''}"
        f"{f' tenants={args.tenants}' if args.tenants else ''}"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = args.out or os.path.join(repo, "artifacts", "bench_serving.json")
    run_done = threading.Event()
    phase = {"name": "init"}  # init -> warmup -> trace
    # the watchdog fires from a daemon thread while main may be wedged
    # inside a dispatch: engines land here after construction so the
    # thread can dump their flight recorders (host-side rings,
    # snapshot-copied under the GIL — best-effort by design)
    holder = {"engines": ()}

    def _run_watchdog():
        if run_done.wait(args.deadline_s) or run_done.is_set():
            return
        # flight-recorder dumps FIRST, path recorded in-band: the whole
        # point of the telemetry layer is that a wedged run still
        # yields a timeline, not a bare {"status": "watchdog"} row
        flight = []
        for i, e in enumerate(holder["engines"]):
            try:
                p = (
                    os.path.join(
                        args.timeline_dir, f"flight_replica{i}_watchdog.json"
                    )
                    if args.timeline_dir
                    else os.path.splitext(os.path.abspath(out))[0]
                    + f".flight{i}.json"
                )
                rec = e.flight_dump(
                    "watchdog", path=p,
                    extra={"replica": i, "phase": phase["name"]},
                )
                flight.append(rec["path"])
            except Exception:  # noqa: BLE001 — a dump must not mask the row
                pass
        row = {
            "status": "watchdog",
            "phase": phase["name"],
            "serve_shape": shape,
            "serve_deadline_s": args.deadline_s,
            "flight_recorder": flight,
            "error": (
                f"serving bench exceeded {args.deadline_s:.0f}s in the "
                f"{phase['name']} phase (wedged TPU relay?)"
            ),
        }
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
        print(json.dumps(row), flush=True)
        os._exit(4)

    threading.Thread(target=_run_watchdog, daemon=True).start()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.config import get_config
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving import ServingEngine

    if args.preset == "tiny":
        from midgpt_tpu.config import ModelConfig

        cfg = ModelConfig(
            block_size=128, vocab_size=256, n_layer=2, n_head=4, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        )
        args.min_prompt, args.max_prompt = 4, 16
        args.min_new, args.max_new = 4, 16
        args.requests = min(args.requests, 16)
        args.rate = 1e9  # arrivals immediate: CPU sanity, not latency
    else:
        cfg = dataclasses.replace(
            get_config("openwebtext").model, attn_impl="auto"
        )
    if args.prompt_len:
        # long-document preset: every prompt exactly --prompt_len tokens
        # (applied AFTER the tiny preset's overrides so it wins), and
        # the model widened to hold the full context — at 100k tokens
        # the widened wpe table is the only parameter that grows
        args.min_prompt = args.max_prompt = args.prompt_len
        need = args.sys_prompt_len + args.prompt_len + args.max_new
        if need > cfg.block_size:
            cfg = dataclasses.replace(cfg, block_size=need)
    assert args.max_prompt + args.max_new <= cfg.block_size, (
        "request mix must fit block_size"
    )
    model = cast_floating(GPT.init(jax.random.PRNGKey(0), cfg), jnp.bfloat16)
    if args.quant == "on":
        # quantize HERE and rebind so the bf16 weights are actually
        # dropped — quantizing inside the engine would leave this
        # binding alive and serve_peak_hbm_bytes would report bf16 +
        # int8 resident, hiding the residency win the flag measures
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)

    rng = np.random.default_rng(args.seed)
    # arrival process — seed-pinned so a trace replays identically:
    # poisson (memoryless, the legacy default), bursty (Poisson burst
    # EPOCHS of --burst_size back-to-back arrivals — flash-crowd
    # shape), diurnal (interarrival rate swept sinusoidally through
    # one "day" over the trace — peak/trough load in one run)
    if args.trace == "bursty":
        n_bursts = -(-args.requests // args.burst_size)
        epochs = np.cumsum(
            rng.exponential(args.burst_size / args.rate, n_bursts)
        )
        arrivals = np.repeat(epochs, args.burst_size)[: args.requests]
    elif args.trace == "diurnal":
        phase = 2.0 * np.pi * np.arange(args.requests) / max(
            1, args.requests
        )
        inst_rate = args.rate * (1.0 + 0.8 * np.sin(phase))
        arrivals = np.cumsum(
            rng.exponential(1.0, args.requests) / np.maximum(
                inst_rate, 1e-9
            )
        )
    else:  # poisson (and the legacy synchronous path)
        arrivals = np.cumsum(
            rng.exponential(1.0 / args.rate, args.requests)
        )
    if args.trace != "off":
        # long-tail prompt lengths: lognormal clipped into the
        # configured band — the realistic mix (most prompts short, a
        # heavy tail of long ones) the chunked-prefill path exists for
        ln = rng.lognormal(
            mean=np.log(max(2.0, args.min_prompt * 2.0)), sigma=0.8,
            size=args.requests,
        )
        plens = np.clip(
            ln.astype(np.int64), args.min_prompt, args.max_prompt
        )
    else:
        plens = rng.integers(
            args.min_prompt, args.max_prompt + 1, args.requests
        )
    nnews = rng.integers(args.min_new, args.max_new + 1, args.requests)
    # scheduling attributes (seed-pinned): priority levels, per-request
    # deadlines, scripted client cancellations
    priorities = (
        rng.integers(0, args.priority_levels, args.requests)
        if args.priority_levels > 1
        else np.zeros(args.requests, np.int64)
    )
    deadlines_s = [
        (args.slo_ms + args.slo_per_token_ms * int(nnews[i])) / 1e3
        if args.slo_ms > 0 else None
        for i in range(args.requests)
    ]
    cancel_mask = rng.random(args.requests) < args.cancel_frac
    cancel_after = [
        int(rng.integers(1, max(2, int(nnews[i]))))
        if cancel_mask[i] else None
        for i in range(args.requests)
    ]
    sys_prompt = rng.integers(
        0, cfg.vocab_size, size=args.sys_prompt_len
    ).astype(np.int32)
    # tenant mix: K distinct system prompts, zipf-ish popularity —
    # the shared-prefix traffic shape at multi-tenant scale (tenant 0
    # hottest, so its prefix chain stays resident across the trace)
    tenant_of = None
    if args.tenants > 0 and args.sys_prompt_len > 0:
        weights = 1.0 / np.arange(1, args.tenants + 1)
        tenant_of = rng.choice(
            args.tenants, size=args.requests, p=weights / weights.sum()
        )
        tenant_prompts = [
            rng.integers(0, cfg.vocab_size, size=args.sys_prompt_len)
            .astype(np.int32)
            for _ in range(args.tenants)
        ]
    shared_mask = rng.random(args.requests) < args.sys_prompt_frac
    if args.repetitive:
        # self-repeating prompts: a short pattern tiled to length — the
        # n-gram proposer finds the period and drafts whole repeats
        def rep_prompt(p):
            pat = rng.integers(
                0, cfg.vocab_size, size=max(2, int(p) // 8)
            ).astype(np.int32)
            return np.tile(pat, -(-int(p) // pat.size))[: int(p)]

        prompts = [rep_prompt(p) for p in plens]
    else:
        prompts = [
            rng.integers(0, cfg.vocab_size, size=int(p)).astype(np.int32)
            for p in plens
        ]
    if args.sys_prompt_len:
        assert args.sys_prompt_len + args.max_prompt + args.max_new <= (
            cfg.block_size
        ), "system prompt + request mix must fit block_size"
        if tenant_of is not None:
            prompts = [
                np.concatenate([tenant_prompts[tenant_of[i]], p])
                for i, p in enumerate(prompts)
            ]
        else:
            prompts = [
                np.concatenate([sys_prompt, p]) if shared_mask[i] else p
                for i, p in enumerate(prompts)
            ]

    from midgpt_tpu.serving import (
        AdmissionRejected,
        ClusterUnavailable,
        FaultPlan,
        PoolOverloaded,
        ServingCluster,
        serving_meshes,
    )

    plan = FaultPlan.parse(args.fault_plan) if args.fault_plan else None
    engine_kw = dict(
        slots=args.slots,
        page_size=args.page_size,
        window=args.window,
        temperature=args.temperature,
        top_k=args.top_k,
        seed=args.seed,
        prefix_cache=args.prefix_cache == "on",
        prefill_chunk=args.prefill_chunk or None,
        speculate=args.spec_len if args.spec == "on" else 0,
        kv_quant="int8" if args.kv_quant == "on" else None,
        paged_kernel=args.paged_kernel,
        layer_scan=args.layer_scan,
        prefill_sp=args.prefill_sp,
        spill=args.spill,
        spill_budget_pages=args.spill_budget_pages or None,
        num_pages=args.num_pages or None,
        max_queue=args.max_queue or None,
        # telemetry=True gives each engine/replica its OWN
        # EngineTelemetry (tracing never touches the compiled programs
        # — the engines still hit the same program cache entries)
        telemetry=args.telemetry == "on",
    )
    # disaggregated pools: '--disagg P+D' replaces the homogeneous
    # --dp_replicas fleet with P prefill-class + D decode-class replicas
    disagg_p = disagg_d = 0
    if args.disagg:
        assert args.dp_replicas == 1, (
            "--disagg P+D and --dp_replicas are mutually exclusive "
            "(disagg fixes the replica count at P+D)"
        )
        parts = args.disagg.split("+")
        assert len(parts) == 2, f"--disagg wants 'P+D', got {args.disagg!r}"
        disagg_p, disagg_d = int(parts[0]), int(parts[1])
    n_replicas = (disagg_p + disagg_d) if args.disagg else args.dp_replicas
    if args.disagg and args.tp == 1 and jax.device_count() < n_replicas:
        # scheduler-correctness mode (the replicas=N documented shape):
        # all pools on the default device — CPU drives of the disagg
        # seam without forcing a host device count
        meshes = [None] * n_replicas
    else:
        meshes = serving_meshes(tp_size=args.tp, dp_replicas=n_replicas)
    # fault injection and the dispatch watchdog live in the cluster's
    # health/failover layer, so chaos runs always drive a cluster (a
    # 1-replica cluster is the degenerate case: faults still degrade
    # into typed outcomes instead of crashing the bench)
    use_cluster = (
        n_replicas > 1
        or plan is not None
        or args.dispatch_timeout_s is not None
    )
    if use_cluster:
        eng = ServingCluster(
            model, meshes=meshes, fault_plan=plan,
            prefill_replicas=disagg_p or None,
            decode_replicas=disagg_d or None,
            affinity=args.affinity == "on",
            dispatch_timeout_s=args.dispatch_timeout_s,
            max_retries=args.max_retries, backoff_s=args.backoff_s,
            # dead-replica flight recorders (crash / watchdog trip /
            # exhausted retries) land next to the timelines, or next to
            # the bench record when no --timeline_dir was given
            flight_dir=(
                args.timeline_dir
                or os.path.dirname(os.path.abspath(out))
            ),
            **engine_kw,
        )
        engines = eng.engines
    else:
        eng = ServingEngine(model, mesh=meshes[0], **engine_kw)
        engines = [eng]
    holder["engines"] = tuple(engines)
    # the engine resolved paged_kernel="auto" to a concrete backend;
    # the watchdog closure reads the rebound name
    shape = shape.replace(
        f"kernel={args.paged_kernel}", f"kernel={engines[0].paged_kernel}"
    )
    # likewise prefill_sp="auto" resolved against the engine's mesh
    # (on iff tensor > 1) — the record and shape carry the live mode
    shape = shape.replace(
        f"sp={args.prefill_sp}", f"sp={engines[0].prefill_sp}"
    )

    # warmup: compile the decode window + EVERY prefill-chunk bucket the
    # trace can dispatch, on EVERY replica. Full-prompt buckets are not
    # enough: with the prefix cache on, admissions prefill arbitrary
    # suffix lengths (and chunking caps them at prefill_chunk), so the
    # cache-on/chunked ladder rungs would otherwise pay XLA compiles
    # inside the timed region — corrupting exactly the comparison they
    # exist for. (DP replicas share program wrappers only when pinned to
    # identical devices — they are not — so each warms its own.)
    phase["name"] = "warmup"
    for e in engines:
        e._fault_hook = None  # chaos must not fire inside warmup
        e.submit(prompts[0], int(nnews[0]))
        if e.role == "prefill":
            # a prefill-class replica never decodes: step to the
            # handoff-ready park (compiling every prefill bucket the
            # trace needs), then export-and-discard to clear the slot
            while e.has_work and not e.handoff_ready_slots():
                e.step()
            for s in e.handoff_ready_slots():
                e.export_request(s)
        else:
            e.run()
        e.warm_prefill(max(p.size for p in prompts))
        e.finished.clear()
        e.clear_prefix_cache()  # measured hit rates: the trace alone
        for attr in ("decode_dispatches", "prefill_dispatches",
                     "copy_dispatches", "tokens_generated", "windows",
                     "occupancy_sum", "evictions", "prompt_tokens_total",
                     "prompt_tokens_cached", "prefill_tokens_computed",
                     "cold_reclaims", "verify_dispatches", "spec_drafted",
                     "spec_accepted", "cancelled_requests",
                     "deadline_shed_requests", "spilled_pages",
                     "spill_faultback_pages", "spill_prefetch_pages",
                     "spill_readmissions", "spill_discards"):
            setattr(e, attr, 0)
        # telemetry + histogram reset: the measured trace's timeline and
        # latency distributions must start at zero like its fault_steps
        # and counters do
        e.metrics.reset_histograms()
        if e.telemetry is not None:
            e.telemetry.reset()
    if use_cluster:
        eng.finished.clear()
        eng._route.clear()
        eng._handoff.clear()
    if plan is not None:
        # re-arm FRESH hooks with step counters at zero: the scripted
        # plan is keyed to the measured trace's scheduler steps, not the
        # warmup's
        for i, e in enumerate(engines):
            e._fault_hook = plan.hook(i)
            e.fault_step = 0
            e.faults_injected = 0

    phase["name"] = "trace"
    status, status_error = "ok", None
    t0 = time.monotonic()
    if args.trace != "off":
        # ---- the async front-door drive (serving.frontdoor) ----
        import asyncio

        from midgpt_tpu.serving import AsyncFrontDoor

        streams: dict = {}  # request index -> TokenStream (the tenant
        # breakdown below needs the per-request terminal outcome)

        async def _drive_trace():
            fd = AsyncFrontDoor(eng)
            consumers = []

            async def consume(i, stream):
                n = 0
                async for _tok in stream:
                    n += 1
                    if cancel_after[i] is not None and n >= cancel_after[i]:
                        stream.cancel()

            async with fd:
                start = time.monotonic()
                for i in range(args.requests):
                    delay = arrivals[i] - (time.monotonic() - start)
                    if delay > 0:
                        await asyncio.sleep(delay)
                    # the SLO anchors at ARRIVAL (absolute deadline on
                    # the engines' monotonic clock): time spent waiting
                    # in submit backpressure counts against it — an
                    # admission-anchored deadline would inflate goodput
                    # exactly under the overload it is meant to measure
                    stream = await fd.submit(
                        prompts[i], int(nnews[i]), seed=i,
                        priority=int(priorities[i]),
                        deadline=(
                            None if deadlines_s[i] is None
                            else start + arrivals[i] + deadlines_s[i]
                        ),
                    )
                    streams[i] = stream
                    consumers.append(
                        asyncio.create_task(consume(i, stream))
                    )
                await asyncio.gather(*consumers)
                await fd.drain()
            return fd

        try:
            fd = asyncio.run(_drive_trace())
            if fd.error is not None:
                raise fd.error
        except ClusterUnavailable as exc:
            status, status_error = "unavailable", str(exc)
    else:
        submitted = 0
        try:
            while submitted < args.requests or eng.has_work:
                now = time.monotonic() - t0
                while (
                    submitted < args.requests
                    and arrivals[submitted] <= now
                ):
                    try:
                        eng.submit(
                            prompts[submitted], int(nnews[submitted]),
                            seed=submitted,
                        )
                    except PoolOverloaded:
                        # bounded queue full (defer, --max_queue): step
                        # below to drain, then retry this arrival — the
                        # synchronous analogue of the front door's
                        # awaitable backpressure
                        break
                    except AdmissionRejected as exc:
                        if exc.reason != "queue_full":
                            raise
                        # shed policy: the request is dropped and
                        # counted by the engine — move on
                    submitted += 1
                progressed = eng.step()
                if not progressed and submitted < args.requests:
                    time.sleep(
                        max(
                            0.0,
                            arrivals[submitted]
                            - (time.monotonic() - t0),
                        )
                    )
        except ClusterUnavailable as exc:
            # every replica died with work pending: still a structured
            # row — the goodput metrics below cover what DID finish
            status, status_error = "unavailable", str(exc)
    wall = time.monotonic() - t0
    t_end = time.monotonic()
    # the watchdog stays armed: the report phase still talks to the
    # device (memory_stats, the tp>1 comms summary re-compiles the
    # window), so a post-trace wedge must still yield a structured row
    phase["name"] = "report"

    # device peak HBM AFTER the trace: the halved weight stream is a
    # residency win too (int8 params + the same KV pool). CPU backends
    # report no memory_stats — emit null rather than a fake number.
    mem = jax.devices()[0].memory_stats() or {}
    peak_hbm = mem.get("peak_bytes_in_use")

    # per-axis comms summary of the sharded decode window (analysis/cost):
    # compile the SAME program geometry (full size, same mesh shape)
    # through the audit harness and attribute each collective's wire
    # bytes to its mesh axis — the static per-dispatch number PERF.md's
    # comms arithmetic is stated against (2 activation psums/layer + the
    # argmax combiner under TP). Cost honesty: this is a second AOT
    # compile of the window (jax's dispatch-path executable cache does
    # not serve .lower().compile()) plus a transient second model/pool
    # on device — it runs AFTER the timed region and the peak-HBM read,
    # so it can only cost queue wall-clock, and a failure here must not
    # lose the bench record. tp=1 has no collectives — emit zeros.
    comms_bytes, comms_by_axis, comms_count = 0, {}, 0
    if args.tp > 1:
        try:
            from midgpt_tpu.analysis import hlo as hlo_mod
            from midgpt_tpu.analysis.cost import cost_report
            from midgpt_tpu.analysis.harness import compile_decode_window
            from midgpt_tpu.analysis.rules import StepAnalysis

            exp = dataclasses.replace(get_config("openwebtext"), model=cfg)
            hlo, amesh, donated, blk, _, _, _ = compile_decode_window(
                exp, slots=args.slots, window=args.window,
                page_size=args.page_size, shrink=False,
                quant=args.quant == "on", mesh_shape={"tensor": args.tp},
            )
            analysis = StepAnalysis.from_text(
                hlo, hlo_mod.MeshInfo.from_mesh(amesh, num_slices=1),
                global_batch=args.slots, block=blk, donated_leaves=donated,
            )
            rep = cost_report(analysis)
            comms_bytes = rep["value"]
            comms_by_axis = rep["by_axis"]
            comms_count = rep["collective_count"]
        except Exception as e:  # noqa: BLE001 — summary is best-effort
            print(f"comms summary skipped: {e}", file=sys.stderr)
            comms_bytes = None

    # static dispatch/launch structure of THIS trace's decode program
    # (analysis.dispatch — the launch-side twin of the byte
    # decomposition below): trace the engine's own decode/verify
    # program geometry and record launches-per-window, the folded
    # layer-scan trip, inlined layer bodies and host transfers next to
    # the measured tok/s, so the fused-vs-unfused r6 rungs carry their
    # static structure in-band. Best-effort like the comms summary —
    # tracing only, after the timed region.
    disp = {}
    try:
        from midgpt_tpu.analysis.dispatch import dispatch_report
        from midgpt_tpu.serving.engine import trace_serving_programs

        jaxprs = trace_serving_programs(
            engines[0].model, slots=args.slots, window=args.window,
            spec_len=max(1, args.spec_len if args.spec == "on" else 1),
            page_size=args.page_size,
            kv_quant="int8" if args.kv_quant == "on" else None,
            paged_kernel=engines[0].paged_kernel,
            layer_scan=args.layer_scan,
        )
        key = "verify" if args.spec == "on" else "decode_window"
        rep = dispatch_report(
            jaxprs[key], program=key,
            window_steps=1 if args.spec == "on" else args.window,
        )
        disp = rep.to_dict()
    except Exception as e:  # noqa: BLE001 — summary is best-effort
        print(f"dispatch summary skipped: {e}", file=sys.stderr)

    # static HBM decomposition for THIS trace's geometry (analysis/
    # traffic.py — the same arithmetic that generates PERF.md's floor
    # table): weight + live-KV + logits streams per decode step at the
    # trace's mean live context, per chip under TP. Recorded next to
    # the measured tok/s so the floor PERF.md compares against is
    # generated, not hand-computed.
    from midgpt_tpu.analysis.traffic import floor_decomposition

    # mean over the FINAL prompt list (includes the shared system
    # prefix and repetitive tiling): those tokens are live KV context
    # during decode exactly like any other prompt token
    live_mean = float(
        np.mean([p.size for p in prompts]) + np.mean(nnews) / 2.0
    )
    static = floor_decomposition(
        cfg, slots=args.slots, live_tokens=live_mean,
        quant=args.quant == "on", kv_quant=args.kv_quant == "on",
        page_size=args.page_size, tp_degree=args.tp,
    )

    ttfts = sorted(
        (r.first_token_time - r.submit_time) * 1e3
        for r in eng.finished.values()
        if r.first_token_time is not None
    )
    pct = (  # noqa: E731
        (lambda q: round(ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))], 1))
        if ttfts else (lambda q: None)
    )
    # long-prompt TTFT lane: the percentile the SP-prefill rung pair
    # ladders. With --prompt_len every request is long by construction;
    # otherwise "long" = the top quartile of the configured prompt band
    # (+ any shared prefix, which prefills like prompt tokens)
    long_thresh = args.prompt_len or (
        args.sys_prompt_len + (3 * args.max_prompt) // 4
    )
    ttfts_long = sorted(
        (r.first_token_time - r.submit_time) * 1e3
        for r in eng.finished.values()
        if r.first_token_time is not None
        and (r.prompt0.size or r.prompt.size) >= long_thresh
    )
    ttft_long_p99 = (
        round(ttfts_long[min(len(ttfts_long) - 1,
                             int(0.99 * len(ttfts_long)))], 1)
        if ttfts_long else None
    )
    # --disagg: TTFT split by the replica class that FINISHED each
    # request (decode-class replicas own every post-handoff first token;
    # prefill-class entries are non-empty only in degraded operation).
    # The engine-level finished dicts survive cluster harvest, so the
    # split reads them directly.
    ttft_by_class = None
    if args.disagg:
        ttft_by_class = {}
        for cls in ("prefill", "decode"):
            vals = sorted(
                (r.first_token_time - r.submit_time) * 1e3
                for e in engines if e.role == cls
                for r in e.finished.values()
                if r.first_token_time is not None
            )
            ttft_by_class[cls] = {
                "n": len(vals),
                "p50_ms": (
                    round(vals[min(len(vals) - 1, len(vals) // 2)], 1)
                    if vals else None
                ),
                "p99_ms": (
                    round(vals[min(len(vals) - 1,
                                   int(0.99 * len(vals)))], 1)
                    if vals else None
                ),
            }
    st = eng.stats()

    # measured-vs-floor attainment + serving MFU (the r6 rungs land
    # self-interpreting): ms/tok measured over the trace vs the static
    # per-token HBM floor above, and the achieved fraction of peak
    # FLOPs at the decode forward's per-token FLOP count — bandwidth
    # and compute ceilings side by side in one row.
    from midgpt_tpu.utils.metrics import (
        decode_flops_per_token,
        device_peak_flops,
    )

    ms_per_tok = (
        wall * 1e3 / st["tokens_generated"]
        if st["tokens_generated"] else None
    )
    n_chips = max(1, args.tp * n_replicas)
    # static SP-prefill compute floor pair (the long-context twin of
    # the HBM decode floor above): prefilling a mean-length prompt
    # costs prompt_tokens x flops-per-token at the prompt's mean live
    # context, compute-bound. The pair BRACKETS the rung pair's
    # measured TTFT — `floor` is the one-chip compute floor (all row
    # work replicated), `sp_floor` divides by tp (every per-row
    # segment sharded over 'tensor'); plain TP already shards the
    # matmul FLOPs, SP additionally shards the replicated per-token
    # segments, so the realized prefill lands between the two.
    prompt_mean = float(np.mean([p.size for p in prompts]))
    prefill_floor_ms = (
        prompt_mean * decode_flops_per_token(cfg, prompt_mean / 2.0)
        / device_peak_flops() * 1e3
    )
    sp_on = engines[0].prefill_sp == "on"
    prefill_sp_floor_ms = prefill_floor_ms / (args.tp if sp_on else 1)
    serve_mfu_v = (
        round(
            (st["tokens_generated"] / wall)
            * decode_flops_per_token(cfg, live_mean)
            / (device_peak_flops() * n_chips), 6,
        )
        if wall > 0 else None
    )

    # per-tenant SLO/goodput breakdown (--trace + --tenants): the zipf
    # tenant mix becomes observable per tenant — which tenants' tokens
    # banked within deadline, not just the aggregate
    tenant_requests = tenant_goodput = tenant_met = None
    if args.trace != "off" and tenant_of is not None:
        tenant_requests = {str(t): 0 for t in range(args.tenants)}
        tenant_met = {str(t): 0 for t in range(args.tenants)}
        _tenant_toks = {str(t): 0 for t in range(args.tenants)}
        for i, s_ in streams.items():
            tkey = str(int(tenant_of[i]))
            tenant_requests[tkey] += 1
            req = s_.request
            if s_.outcome == "finished" and req is not None and (
                req.deadline is None
                or (
                    req.finish_time is not None
                    and req.finish_time <= req.deadline
                )
            ):
                tenant_met[tkey] += 1
                _tenant_toks[tkey] += len(req.tokens)
        tenant_goodput = {
            t: round(n / wall, 1) for t, n in _tenant_toks.items()
        }

    # Prometheus text exposition over the metrics registry (engine or
    # cluster + replicas) — the scrape-format twin of the
    # metrics_snapshot.json artifact
    metrics_out_path = None
    if args.metrics_out:
        from midgpt_tpu.telemetry import prometheus_text

        metrics_out_path = os.path.abspath(args.metrics_out)
        os.makedirs(
            os.path.dirname(metrics_out_path) or ".", exist_ok=True
        )
        with open(metrics_out_path, "w") as f:
            f.write(prometheus_text(eng.metrics_snapshot()))

    # telemetry-derived per-request latency percentiles + timeline
    # artifacts (serving.telemetry). TBT granularity honesty: the
    # engine emits tokens in window batches, so the per-token gaps are
    # the HARVEST cadence a streaming client would see (0 within one
    # fused window, the window wall time across windows) — the p99 is
    # the interesting lane, the p50 collapses toward 0 as K grows.
    from midgpt_tpu.serving.telemetry import (
        chrome_trace,
        percentile,
        write_json,
    )

    teles = [
        (i, e.telemetry)
        for i, e in enumerate(engines)
        if e.telemetry is not None
    ]
    req_metrics = [m for _, t in teles for m in t.finished_request_metrics()]
    tbts = sorted(dt * 1e3 for m in req_metrics for dt in m["tbt_s"])
    qdelays = sorted(
        m["queue_delay_s"] * 1e3
        for m in req_metrics
        if m["queue_delay_s"] is not None
    )
    pms = (  # noqa: E731
        lambda vals, q: (
            round(percentile(vals, q), 3) if vals else None
        )
    )
    timeline_files = []
    if args.timeline_dir and teles:
        for i, t in teles:
            timeline_files.append(write_json(
                os.path.join(args.timeline_dir, f"timeline_replica{i}.json"),
                chrome_trace(t),
            ))
        timeline_files.append(write_json(
            os.path.join(args.timeline_dir, "request_metrics.json"),
            {"requests": req_metrics},
        ))
        # the registry snapshot (counters + gauges + histograms) rides
        # along so an r6 rung's row has its dispatch-level breakdown
        # next to the ms/tok headline
        timeline_files.append(write_json(
            os.path.join(args.timeline_dir, "metrics_snapshot.json"),
            eng.metrics_snapshot(),
        ))
    # goodput under faults: each finished request's tokens count exactly
    # once, however many times faults made the engines recompute them.
    # serve_tok_s (tokens_generated) stays the raw engine WORK rate — a
    # warm failover carries emitted tokens to the survivor (no recount),
    # but a COLD one re-serves from scratch, so the dead replica's
    # progress is generated twice; the gap between the two rates is the
    # throughput the faults burned.
    good_tokens = sum(len(r.tokens) for r in eng.finished.values())
    # goodput UNDER SLO (the trace-replay headline): only tokens from
    # requests that finished WITHIN their deadline bank — a late finish
    # is engine work (serve_tok_s) that earned nothing, a pre-dispatch
    # shed never became work at all. Without --slo_ms every finish
    # counts (goodput_slo == goodput).
    met = [
        r for r in eng.finished.values()
        if r.deadline is None or (
            r.finish_time is not None and r.finish_time <= r.deadline
        )
    ]
    slo_tokens = sum(len(r.tokens) for r in met)
    n_missed = len(eng.finished) - len(met)
    n_cancelled = len(getattr(eng, "cancelled", {}))
    n_expired = len(getattr(eng, "expired", {}))
    # recovery: wall-clock from the first replica death to trace drain
    first_fault = getattr(eng, "first_fault_time", None)
    record = {
        "device": jax.devices()[0].device_kind,
        "status": status,
        "serve_shape": shape,
        "serve_tp": args.tp,
        "serve_dp_replicas": args.dp_replicas,
        "serve_comms_bytes_per_dispatch": comms_bytes,
        "serve_comms_by_axis": comms_by_axis,
        "serve_comms_collective_count": comms_count,
        "serve_quant": args.quant,
        "serve_kv_quant": args.kv_quant,
        # requested vs resolved: "auto" resolves post-supported(), and a
        # long-context row claiming pallas must not hide an XLA fallback
        "serve_paged_kernel": args.paged_kernel,
        "serve_paged_kernel_resolved": engines[0].paged_kernel,
        "serve_layer_scan": args.layer_scan,
        "serve_static_launches_per_window": disp.get("launches_per_window"),
        "serve_static_inlined_layer_bodies": disp.get(
            "inlined_layer_bodies"
        ),
        "serve_static_layer_scan_length": disp.get("layer_scan_length"),
        "serve_static_host_transfers": disp.get("host_transfers"),
        "serve_peak_hbm_bytes": peak_hbm,
        "serve_bytes_per_token_static": static["bytes_per_token"],
        "serve_bytes_per_step_static": static["bytes_per_step"],
        "serve_weights_bytes_per_step_static": static[
            "weights_bytes_per_step"
        ],
        "serve_kv_bytes_per_step_static": static["kv_bytes_per_step"],
        "serve_hbm_floor_ms_static": static["floor_ms_per_step"],
        "serve_floor_ms_per_tok_static": static["floor_ms_per_token"],
        "serve_ms_per_tok": (
            round(ms_per_tok, 4) if ms_per_tok is not None else None
        ),
        # attainment = floor / measured: 1.0 means the decode step runs
        # at the HBM roofline; the residual is dispatch structure +
        # [B,1,D] matmul inefficiency (PERF.md's gap decomposition,
        # now measured in-band instead of hand-derived)
        "serve_attainment_frac": (
            # significant digits, not decimals: tiny-preset CPU rows sit
            # at ~1e-4 and must not round to a hard zero
            float(f"{static['floor_ms_per_token'] / ms_per_tok:.3g}")
            if ms_per_tok else None
        ),
        "serve_mfu": serve_mfu_v,
        "serve_static_live_tokens": round(live_mean, 1),
        "serve_requests": args.requests,
        "serve_rate_req_s": args.rate if args.preset != "tiny" else None,
        "serve_wall_s": round(wall, 3),
        "serve_tok_s": round(st["tokens_generated"] / wall, 1),
        "serve_ttft_p50_ms": pct(0.50),
        "serve_ttft_p99_ms": pct(0.99),
        # long-context serving (sequence-parallel prefill + host-RAM
        # cold-page spill): the resolved SP mode, the long-prompt TTFT
        # lane the sp off/on rung pair ladders, the static prefill
        # compute floor pair that brackets it (one-chip floor vs the
        # fully-row-sharded /tp ideal), and the spill counters that
        # price the host round-trips under pool pressure
        "serve_prefill_sp": engines[0].prefill_sp,
        "serve_prompt_len": args.prompt_len or None,
        "serve_ttft_long_p99": ttft_long_p99,
        "serve_prefill_floor_ms_static": round(prefill_floor_ms, 4),
        "serve_prefill_sp_floor_ms_static": round(prefill_sp_floor_ms, 4),
        "serve_spill": args.spill,
        "serve_num_pages": engines[0].alloc.num_pages,
        "serve_spilled_pages": st.get("spilled_pages", 0),
        "serve_spill_faultback_pages": st.get("spill_faultback_pages", 0),
        "serve_spill_prefetch_pages": st.get("spill_prefetch_pages", 0),
        "serve_spill_readmissions": st.get("spill_readmissions", 0),
        "serve_spill_discards": st.get("spill_discards", 0),
        "serve_spill_resident_pages": st.get("spill_resident_pages", 0),
        # disaggregated pools + affinity routing (serving.cluster)
        "serve_disagg": args.disagg,
        "serve_affinity": args.affinity,
        "serve_ttft_by_class": ttft_by_class,
        "serve_handoff_count": st.get("handoffs", 0),
        "serve_handoff_pages": st.get("handoff_pages_moved", 0),
        "serve_handoff_bytes": st.get("handoff_bytes", 0),
        "serve_handoff_failures": st.get("handoff_failures", 0),
        "serve_prefix_affinity_hits": st.get("prefix_affinity_hits", 0),
        "serve_routed_fallback": st.get("routed_fallback", 0),
        # telemetry-derived (serving.telemetry; null with --telemetry
        # off): time-between-tokens at the harvest cadence and
        # submit->first-admission queue delay
        "serve_telemetry": args.telemetry,
        "serve_tbt_p50_ms": pms(tbts, 0.50),
        "serve_tbt_p99_ms": pms(tbts, 0.99),
        "serve_queue_delay_p50_ms": pms(qdelays, 0.50),
        "serve_queue_delay_p99_ms": pms(qdelays, 0.99),
        "serve_timeline_files": timeline_files or None,
        "serve_flight_dumps": (
            list(eng.flight_dumps) if use_cluster else []
        ) or None,
        "serve_slot_occupancy": st["slot_occupancy"],
        "serve_decode_dispatches": st["decode_dispatches"],
        "serve_prefill_dispatches": st["prefill_dispatches"],
        "serve_tokens_generated": st["tokens_generated"],
        "serve_tokens_per_dispatch": st["tokens_per_dispatch"],
        "serve_evictions": st["evictions"],
        "serve_prefix_hit_rate": st["prefix_hit_rate"],
        "serve_prefill_tokens_saved": st["prefill_tokens_saved"],
        "serve_prefill_tokens_computed": st["prefill_tokens_computed"],
        "serve_cow_copies": st["copy_dispatches"],
        "serve_cold_reclaims": st["cold_reclaims"],
        "serve_verify_dispatches": st["verify_dispatches"],
        "serve_spec_drafted_tokens": st["spec_drafted_tokens"],
        "serve_spec_accepted_tokens": st["spec_accepted_tokens"],
        "serve_spec_acceptance_rate": st["spec_acceptance_rate"],
        # sampling shape: temperature 0 = greedy; > 0 composes with
        # --spec on via rejection-sampling verification, and the
        # acceptance rate above is the sampled accept fraction
        "serve_temperature": args.temperature,
        "serve_top_k": args.top_k,
        # trace replay / SLO accounting (serving.frontdoor)
        "serve_trace": args.trace,
        "serve_slo_ms": args.slo_ms or None,
        "serve_priority_levels": args.priority_levels,
        "serve_cancel_frac": args.cancel_frac,
        "serve_tenants": args.tenants or None,
        "serve_tenant_requests": tenant_requests,
        "serve_tenant_goodput": tenant_goodput,
        "serve_tenant_deadline_met": tenant_met,
        "serve_metrics_out": metrics_out_path,
        "serve_goodput_slo_tok_s": round(slo_tokens / wall, 1),
        "serve_deadline_met": len(met),
        "serve_deadline_missed": n_missed,
        "serve_deadline_shed": st.get("deadline_shed_requests", 0),
        "serve_cancelled": n_cancelled,
        "serve_expired_requests": n_expired,
        # fault tolerance / overload degradation (serving.faults)
        "serve_fault_plan": args.fault_plan,
        "serve_requests_finished": len(eng.finished),
        "serve_goodput_tok_s": round(good_tokens / wall, 1),
        "serve_faults_injected": st.get("faults_injected", 0),
        "serve_admission_rejected": st.get("admission_rejected", 0),
        "serve_reject_reasons": st.get("reject_reasons", {}),
        "serve_shed_requests": st.get("shed_requests", 0),
        "serve_deferred_submits": st.get("deferred_submits", 0),
        "serve_livelock_parks": st.get("livelock_parks", 0),
        "serve_overload_parks": st.get("overload_parks", 0),
        "serve_watchdog_trips": st.get("watchdog_trips", 0),
        "serve_retries": st.get("retries", 0),
        "serve_failovers": st.get("failovers", 0),
        "serve_requeued_requests": st.get("requeued_requests", 0),
        "serve_dead_replicas": st.get("dead_replicas", 0),
        "serve_replica_health": st.get(
            "replica_health", ["healthy"] * len(engines)
        ),
        "serve_recovery_s": (
            round(t_end - first_fault, 3) if first_fault is not None
            else None
        ),
        "serve_error": status_error,
    }
    run_done.set()  # record complete: main owns the output line now
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
