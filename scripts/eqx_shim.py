"""Minimal equinox-compatible shim so the REFERENCE implementation
(/root/reference — pure JAX + Equinox) can run in this image, where
equinox is not installed and cannot be (zero egress).

Used ONLY by scripts/check_reference_parity.py to produce the
side-by-side loss-parity measurement (VERDICT r3 Missing #1). Implements
exactly the API surface the reference uses (grep over /root/reference:
Module, field, is_array, partition/combine/filter, filter_jit,
filter_vmap, Partial, tree_pprint, nn.Dropout, nn.LayerNorm) with
equinox's semantics for those calls — nothing more.

Install with:  sys.modules["equinox"] = make_equinox_module()
BEFORE importing the reference package.
"""

from __future__ import annotations

import functools
import types
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np


class _FieldSpec:
    def __init__(self, static: bool = False):
        self.static = static


def field(*, static: bool = False, **_kw):
    return _FieldSpec(static=static)


def is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _collect_fields(cls) -> tp.Tuple[tp.Tuple[str, ...], tp.Tuple[str, ...]]:
    """(dynamic_field_names, static_field_names) in annotation order
    across the MRO (base classes first), deduplicated."""
    dyn, static = [], []
    seen = set()
    for klass in reversed(cls.__mro__):
        for name in getattr(klass, "__annotations__", {}):
            if name in seen or name.startswith("__"):
                continue
            seen.add(name)
            spec = klass.__dict__.get(name)
            if isinstance(spec, _FieldSpec) and spec.static:
                static.append(name)
            else:
                dyn.append(name)
    return tuple(dyn), tuple(static)


class Module:
    """Equinox-style module: annotated fields form a pytree; fields
    declared with ``field(static=True)`` ride in the treedef aux data."""

    _dyn_fields: tp.ClassVar[tp.Tuple[str, ...]] = ()
    _static_fields: tp.ClassVar[tp.Tuple[str, ...]] = ()

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        cls._dyn_fields, cls._static_fields = _collect_fields(cls)

        def flatten(obj):
            children = tuple(getattr(obj, f) for f in cls._dyn_fields)
            aux = tuple(getattr(obj, f) for f in cls._static_fields)
            return children, aux

        def unflatten(aux, children):
            obj = object.__new__(cls)
            for f, v in zip(cls._dyn_fields, children):
                object.__setattr__(obj, f, v)
            for f, v in zip(cls._static_fields, aux):
                object.__setattr__(obj, f, v)
            return obj

        jax.tree_util.register_pytree_node(cls, flatten, unflatten)


class Partial(Module):
    """Pytree-aware functools.partial (the reference wraps a model with
    ``inference=True`` for evaluation)."""

    func: tp.Any
    args: tp.Tuple
    keywords: tp.Dict[str, tp.Any]

    def __init__(self, func, *args, **kwargs):
        self.func = func
        self.args = args
        self.keywords = kwargs

    def __call__(self, *args, **kwargs):
        return self.func(*self.args, *args, **{**self.keywords, **kwargs})


_MISSING = object()


def _is_none(x) -> bool:
    return x is None


def partition(tree, filter_fn):
    """(matching, rest) — non-matching leaves replaced by None and vice
    versa, same treedef. Mirrors eqx.partition for leaf-level filters."""
    dynamic = jax.tree_util.tree_map(
        lambda x: x if filter_fn(x) else None, tree
    )
    static = jax.tree_util.tree_map(
        lambda x: None if filter_fn(x) else x, tree
    )
    return dynamic, static


def combine(*trees):
    def pick(*vals):
        for v in vals:
            if v is not None:
                return v
        return None

    return jax.tree_util.tree_map(pick, *trees, is_leaf=_is_none)


def filter(tree, filter_fn):  # noqa: A001 — equinox's name
    return partition(tree, filter_fn)[0]


def _static_key(static) -> tp.Hashable:
    leaves, treedef = jax.tree_util.tree_flatten(static)
    return (treedef, tuple(leaves))


def filter_jit(fn=None, *, donate: str = "none"):
    """jit that traces array leaves and treats everything else as static
    (cached per static-structure so jit's own compile cache applies)."""
    if fn is None:
        return functools.partial(filter_jit, donate=donate)
    cache: tp.Dict[tp.Hashable, tp.Any] = {}

    @functools.wraps(fn)
    def wrapper(*args):
        dynamic, static = partition(args, is_array)
        key = _static_key(static)
        if key not in cache:
            out_static = {}

            def run(dyn, _static=static):
                merged = combine(dyn, _static)
                out = fn(*merged)
                # non-array outputs ride outside the jit, like equinox
                out_dyn, out_static["v"] = partition(out, is_array)
                return out_dyn

            cache[key] = (
                jax.jit(run, donate_argnums=(0,) if donate == "all" else ()),
                out_static,
            )
        jitted, out_static = cache[key]
        out_dyn = jitted(dynamic)
        return combine(out_dyn, out_static["v"])

    return wrapper


def filter_vmap(fn):
    """vmap where array outputs are batched and non-array outputs are
    captured unbatched (enough for the reference's stacked-Block init)."""

    def wrapper(*args):
        captured = {}

        def inner(*a):
            out = fn(*a)
            dyn, static = partition(out, is_array)
            captured["static"] = static
            return dyn

        dyn = jax.vmap(inner)(*args)
        return combine(dyn, captured["static"])

    return wrapper


def tree_pprint(tree, **kw):  # pragma: no cover — cosmetic
    print(jax.tree_util.tree_structure(tree))


class _Dropout(Module):
    p: float
    inference: bool

    def __init__(self, p: float = 0.5, inference: bool = False):
        self.p = p
        self.inference = inference

    def __call__(self, x, *, key=None, inference=None):
        inference = self.inference if inference is None else inference
        if inference or self.p == 0.0:
            return x
        if key is None:
            raise RuntimeError("Dropout requires a key when not inference")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class _LayerNorm(Module):
    shape: tp.Any
    eps: float
    use_weight: bool
    use_bias: bool
    weight: tp.Optional[jax.Array]
    bias: tp.Optional[jax.Array]

    def __init__(self, shape, eps: float = 1e-5, use_weight: bool = True,
                 use_bias: bool = True, **_kw):
        self.shape = shape
        self.eps = eps
        self.use_weight = use_weight
        self.use_bias = use_bias
        self.weight = jnp.ones(shape) if use_weight else None
        self.bias = jnp.zeros(shape) if use_bias else None

    def __call__(self, x, *, key=None):
        mean = jnp.mean(x, keepdims=True)
        variance = jnp.var(x, keepdims=True)
        inv = jax.lax.rsqrt(variance + self.eps)
        out = (x - mean) * inv
        if self.weight is not None:
            out = out * self.weight
        if self.bias is not None:
            out = out + self.bias
        return out


def make_equinox_module() -> types.ModuleType:
    eqx = types.ModuleType("equinox")
    eqx.Module = Module
    eqx.field = field
    eqx.is_array = is_array
    eqx.partition = partition
    eqx.combine = combine
    eqx.filter = filter
    eqx.filter_jit = filter_jit
    eqx.filter_vmap = filter_vmap
    eqx.Partial = Partial
    eqx.tree_pprint = tree_pprint
    nn = types.ModuleType("equinox.nn")
    nn.Dropout = _Dropout
    nn.LayerNorm = _LayerNorm
    eqx.nn = nn
    return eqx
