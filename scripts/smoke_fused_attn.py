"""On-chip smoke: projection-natural fused attention (QK-LN+RoPE+flash).

Runs on the REAL TPU (not interpret mode — r2's transpose-free post-mortem
proved Mosaic can reject layouts the interpreter accepts, PERF.md):
  1. fwd + bwd parity vs the unfused jnp oracle at the 124M MHA shape
     (C=64 head-pair mode) and the llama GQA shape (C=128).
  2. microbench fused vs the current unfused path (LN+rope+transposes
     around ops.flash), fwd and fwd+bwd.

Usage: PYTHONPATH=. python scripts/smoke_fused_attn.py [--quick]
Writes artifacts/smoke_fused_attn.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _chain_time(fn, args, n=20):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    leaves = jax.tree.leaves(out)
    _ = float(jnp.sum(leaves[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / n * 1e3


def _setup(b, t, h, hkv, c, dtype, seed=0):
    from midgpt_tpu.models.layers import rope_tables

    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    q = jax.random.normal(ks[0], (b, t, h * c), dtype)
    k = jax.random.normal(ks[1], (b, t, hkv * c), dtype)
    v = jax.random.normal(ks[2], (b, t, hkv * c), dtype)
    wq = 1.0 + 0.1 * jax.random.normal(ks[3], (c,), jnp.float32)
    wk = 1.0 + 0.1 * jax.random.normal(ks[4], (c,), jnp.float32)
    sin_h, cos_h = rope_tables(c, t)
    sin = jnp.asarray(np.repeat(sin_h, 2, axis=-1), jnp.float32)
    cos = jnp.asarray(np.repeat(cos_h, 2, axis=-1), jnp.float32)
    return q, k, v, wq, wk, sin, cos


def parity_case(name, b, t, h, hkv, c, record):
    from midgpt_tpu.ops.fused_attn import (
        fused_attention,
        fused_attention_reference,
    )

    q, k, v, wq, wk, sin, cos = _setup(b, t, h, hkv, c, jnp.bfloat16)
    w_out = jax.random.normal(jax.random.PRNGKey(9), (h * c,), jnp.float32)

    def loss_fused(q, k, v, wq, wk):
        out = fused_attention(q, k, v, wq, wk, sin, cos, h, hkv)
        return jnp.sum(out.astype(jnp.float32) * w_out)

    def loss_ref(q, k, v, wq, wk):
        out = fused_attention_reference(q, k, v, wq, wk, sin, cos, h, hkv)
        return jnp.sum(out.astype(jnp.float32) * w_out)

    out = jax.jit(
        lambda *a: fused_attention(*a, sin, cos, h, hkv)
    )(q, k, v, wq, wk)
    ref = jax.jit(
        lambda *a: fused_attention_reference(*a, sin, cos, h, hkv)
    )(q, k, v, wq, wk)
    fwd_err = float(
        jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
    )

    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4)))(q, k, v, wq, wk)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4)))(q, k, v, wq, wk)
    gerrs = {}
    for gname, a_, b_ in zip(["dq", "dk", "dv", "dwq", "dwk"], gf, gr):
        denom = float(jnp.max(jnp.abs(b_.astype(jnp.float32)))) + 1e-6
        gerrs[gname] = float(
            jnp.max(jnp.abs(a_.astype(jnp.float32) - b_.astype(jnp.float32)))
        ) / denom
    record[name] = {"fwd_max_abs_err": fwd_err, "grad_max_rel_err": gerrs}
    ok = fwd_err < 0.1 and all(e < 0.05 for e in gerrs.values())
    print(f"[{name}] fwd_err={fwd_err:.4f} grad_rel_errs={gerrs} -> "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def bench_case(name, b, t, h, hkv, c, record):
    from midgpt_tpu.models.layers import LayerNorm, apply_rotary, rope_tables
    from midgpt_tpu.ops.flash import flash_attention
    from midgpt_tpu.ops.fused_attn import fused_attention

    q, k, v, wq, wk, sin, cos = _setup(b, t, h, hkv, c, jnp.bfloat16)
    sin_h, cos_h = rope_tables(c, t)
    qn = LayerNorm(weight=wq)
    kn = LayerNorm(weight=wk)

    def unfused(q, k, v, qn, kn):
        qh = qn(q.reshape(b, t, h, c))
        kh = kn(k.reshape(b, t, hkv, c))
        vh = v.reshape(b, t, hkv, c)
        qh = jnp.transpose(qh, (0, 2, 1, 3))
        kh = jnp.transpose(kh, (0, 2, 1, 3))
        vh = jnp.transpose(vh, (0, 2, 1, 3))
        qh = apply_rotary(qh, sin_h, cos_h)
        kh = apply_rotary(kh, sin_h, cos_h)
        o = flash_attention(qh, kh, vh)
        return jnp.transpose(o, (0, 2, 1, 3)).reshape(b, t, h * c)

    def fused(q, k, v, wq, wk):
        return fused_attention(q, k, v, wq, wk, sin, cos, h, hkv)

    r = {}
    r["unfused_fwd_ms"] = _chain_time(unfused, (q, k, v, qn, kn))
    r["fused_fwd_ms"] = _chain_time(fused, (q, k, v, wq, wk))

    def g(fn, nargs):
        def loss(*a):
            return jnp.sum(fn(*a).astype(jnp.float32))

        return jax.grad(loss, argnums=tuple(range(nargs)))

    r["unfused_fb_ms"] = _chain_time(g(unfused, 3), (q, k, v, qn, kn))
    r["fused_fb_ms"] = _chain_time(g(fused, 5), (q, k, v, wq, wk))
    record[name + "_bench"] = r
    print(f"[{name}] unfused fwd {r['unfused_fwd_ms']:.2f} / fused fwd "
          f"{r['fused_fwd_ms']:.2f} ms ; unfused f+b {r['unfused_fb_ms']:.2f}"
          f" / fused f+b {r['fused_fb_ms']:.2f} ms")


def main():
    quick = "--quick" in sys.argv
    record = {"device": jax.devices()[0].device_kind}
    ok = parity_case("gpt2s_mha_c64", 4, 1024, 12, 12, 64, record)
    ok &= parity_case("llama_gqa_c128", 2, 2048, 8, 2, 128, record)
    if not quick:
        bench_case("gpt2s_shape", 16, 1024, 12, 12, 64, record)
        bench_case("llama_shape", 4, 2048, 16, 4, 128, record)
    record["ok"] = bool(ok)
    os.makedirs("artifacts", exist_ok=True)
    with open("artifacts/smoke_fused_attn.json", "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
