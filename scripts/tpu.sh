#!/usr/bin/env bash
# TPU fleet operations CLI (capability parity: reference
# scripts/tpu_commands.sh:184-251 — list/describe/create/delete/setup/copy/
# launch/check/stop/ssh/reboot/maintain — rebuilt for this framework).
#
# Usage:
#   scripts/tpu.sh <verb> [args...]
#
# Configuration comes from env vars (no hardcoded project/zone like the
# reference, tpu_commands.sh:188-200):
#   TPU_PROJECT   gcloud project            (required for gcloud verbs)
#   TPU_ZONE      e.g. us-east5-a
#   TPU_NAME      TPU VM name
#   TPU_TYPE      accelerator type, e.g. v5p-128 (create)
#   TPU_VERSION   runtime version, e.g. v2-alpha-tpuv5 (create)
#   TPU_REPO_DIR  remote checkout path (default: ~/midgpt_tpu)
#   TPU_DATA_DISK dataset persistent disk to attach+mount at
#                 /mnt/disks/persist during `setup` (optional)
set -euo pipefail

REPO_DIR_REMOTE="${TPU_REPO_DIR:-\$HOME/midgpt_tpu}"

need() {
  for v in "$@"; do
    [[ -n "${!v:-}" ]] || { echo "error: \$$v must be set" >&2; exit 1; }
  done
}

gc() { gcloud compute tpus tpu-vm "$@" --project "$TPU_PROJECT" --zone "$TPU_ZONE"; }

# Run a command on every host of the slice, in parallel, through gcloud ssh.
all_hosts() {
  need TPU_PROJECT TPU_ZONE TPU_NAME
  gc ssh "$TPU_NAME" --worker=all --command="$1"
}

cmd="${1:-help}"; shift || true
case "$cmd" in
  list)
    need TPU_PROJECT TPU_ZONE
    gcloud compute tpus tpu-vm list --project "$TPU_PROJECT" --zone "$TPU_ZONE"
    ;;
  describe)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    gc describe "$TPU_NAME"
    ;;
  ips)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    # gcloud joins repeated fields with ';' — emit one IP per line
    gc describe "$TPU_NAME" \
      --format='value(networkEndpoints[].accessConfig.externalIp)' \
      | tr ';' '\n' | sed '/^$/d'
    ;;
  create)
    need TPU_PROJECT TPU_ZONE TPU_NAME TPU_TYPE TPU_VERSION
    gc create "$TPU_NAME" \
      --accelerator-type "$TPU_TYPE" --version "$TPU_VERSION" "$@"
    ;;
  retry_create)
    # loop on stockout/quota errors (parity: tpu_commands.sh:40-45);
    # config errors fail fast, and the loop is bounded
    need TPU_PROJECT TPU_ZONE TPU_NAME TPU_TYPE TPU_VERSION
    attempts="${TPU_RETRY_LIMIT:-120}"
    until "$0" create "$@"; do
      attempts=$((attempts - 1))
      [[ $attempts -gt 0 ]] || { echo "retry limit reached" >&2; exit 1; }
      echo "create failed; retrying in 60s ($attempts attempts left)..." >&2
      sleep 60
    done
    ;;
  delete)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    gc delete "$TPU_NAME" --quiet
    ;;
  setup)
    # install deps on every host (parity: setup.sh:8-10), then attach and
    # mount the dataset persistent disk when TPU_DATA_DISK is set (parity:
    # setup.sh:13-19 — the openwebtext configs point at /mnt/disks/persist)
    all_hosts "pip install -q -U 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html optax orbax-checkpoint tqdm wandb gcsfs tiktoken"
    if [[ -n "${TPU_DATA_DISK:-}" ]]; then
      # idempotent: re-running setup must not die on "already attached"
      if ! gcloud alpha compute tpus tpu-vm attach-disk "$TPU_NAME" \
          --project "$TPU_PROJECT" --zone "$TPU_ZONE" \
          --disk "$TPU_DATA_DISK" --mode=read-only 2>/tmp/attach_err; then
        grep -qi "already attached" /tmp/attach_err \
          || { cat /tmp/attach_err >&2; exit 1; }
      fi
      # find the device by disk name ONLY — never guess /dev/sdb
      # (enumeration order is unstable; a wrong-disk mount passes a bare
      # readability check and silently strands the openwebtext runs —
      # ADVICE r3). Verify the mount actually holds the dataset dir.
      marker="${TPU_DATA_MARKER:-openwebtext}"
      all_hosts "set -e; \
        dev=\$(readlink -f /dev/disk/by-id/google-${TPU_DATA_DISK} 2>/dev/null || true); \
        if [ ! -b \"\$dev\" ]; then \
          echo \"ERROR: /dev/disk/by-id/google-${TPU_DATA_DISK} not found;\" \
               'refusing to guess a device (unstable enumeration)' >&2; \
          ls -l /dev/disk/by-id/ >&2 || true; exit 1; \
        fi; \
        sudo mkdir -p /mnt/disks/persist; \
        mountpoint -q /mnt/disks/persist || \
          sudo mount -o ro,noload \"\$dev\" /mnt/disks/persist; \
        if [ ! -e \"/mnt/disks/persist/${marker}\" ]; then \
          echo \"ERROR: mounted ${TPU_DATA_DISK} but\" \
               \"/mnt/disks/persist/${marker} is missing — wrong disk?\" \
               '(set TPU_DATA_MARKER to the expected data dir)' >&2; \
          exit 1; \
        fi"
    else
      echo "note: TPU_DATA_DISK not set; skipping dataset-disk attach/mount" >&2
    fi
    ;;
  copy)
    # rsync the local checkout to every host (parity: tpu_commands.sh copy)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    src="$(cd "$(dirname "$0")/.." && pwd)"
    for ip in $("$0" ips); do
      rsync -az --exclude outputs --exclude .git --exclude '*.so' \
        "$src/" "$ip:${REPO_DIR_REMOTE#\$HOME/}/" &
    done
    wait
    ;;
  launch)
    # start training in a detached tmux on every host
    # usage: tpu.sh launch <config> <rundir> [extra launch.py args...]
    config="${1:?usage: tpu.sh launch <config> <rundir> [args...]}"; shift
    rundir="${1:?rundir required for multihost}"; shift
    all_hosts "cd $REPO_DIR_REMOTE && tmux new-session -d -s train \
      'python launch.py --config=$config --rundir=$rundir --multihost $* 2>&1 | tee train.log'"
    ;;
  check)
    # tail the training log on every host (parity: tpu_commands.sh:79-91)
    all_hosts "tail -n ${1:-20} $REPO_DIR_REMOTE/train.log"
    ;;
  stop)
    all_hosts "tmux kill-session -t train || true"
    ;;
  ssh)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    gc ssh "$TPU_NAME" --worker="${1:-0}"
    ;;
  reboot)
    all_hosts "sudo reboot" || true
    ;;
  maintain)
    # rehearse preemption + checkpoint resume (parity: tpu_commands.sh:142-151)
    need TPU_PROJECT TPU_ZONE TPU_NAME
    gc simulate-maintenance-event "$TPU_NAME" --workers=all
    ;;
  df)
    all_hosts "df -h | head -5"
    ;;
  help|*)
    sed -n '2,16p' "$0"
    echo "verbs: list describe ips create retry_create delete setup copy launch check stop ssh reboot maintain df"
    ;;
esac
