"""Profile one training step of any named config on the current devices.

    python scripts/profile_step.py --config=openwebtext --outdir=/tmp/prof \
        [--set model.n_layer=4 ...]

Writes a TensorBoard-compatible trace (xplane) to <outdir>; inspect with
tensorboard-plugin-profile. Equivalent of the reference's --debug step-0
trace (/root/reference/src/train.py:205-211) as a standalone tool, usable
without starting a run.
"""

from __future__ import annotations

import argparse
import dataclasses

import jax
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--outdir", required=True)
    ap.add_argument("--batch", type=int, default=None, help="override global batch")
    ap.add_argument("--steps", type=int, default=3, help="steps inside the trace")
    ap.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    args = ap.parse_args()

    from launch import apply_overrides
    from midgpt_tpu.config import get_config
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step
    from jax.sharding import PartitionSpec as P

    cfg = apply_overrides(get_config(args.config), args.set)
    if args.batch is not None:
        cfg = dataclasses.replace(cfg, batch_size=args.batch, g_accum_iters=1)

    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)

    t = cfg.model.block_size
    g, b = cfg.g_accum_iters, cfg.batch_size // cfg.g_accum_iters
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.model.vocab_size, size=(g, b, t), dtype=np.int32)
    y = rng.integers(0, cfg.model.vocab_size, size=(g, b, t), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg, yg = make_global_array(x, mesh, spec), make_global_array(y, mesh, spec)
    key = jax.random.PRNGKey(1)

    # warmup/compile outside the trace
    state, loss = step(state, xg, yg, key)
    jax.block_until_ready(loss)

    with jax.profiler.trace(args.outdir):
        for _ in range(args.steps):
            state, loss = step(state, xg, yg, key)
        jax.block_until_ready(loss)
    print(f"traced {args.steps} steps of {args.config} -> {args.outdir}")


if __name__ == "__main__":
    main()
