"""Multihost TPU sanity smoke (parity: reference scripts/test_jax.py:34-58).

Run on every host of a slice (e.g. via ``scripts/tpu.sh launch``-style ssh
fan-out):

    python scripts/smoke_tpu.py [--multihost]

Builds the framework's 4-axis mesh over all devices, assembles a global
array from per-host shards, runs a jitted sharded matmul, and prints the
sharding layout from process 0.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--multihost", action="store_true")
    args = ap.parse_args()
    if args.multihost:
        jax.distributed.initialize()

    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array

    proc = jax.process_index()
    print(f"[proc {proc}] {jax.process_count()} processes, "
          f"{jax.device_count()} devices ({jax.local_device_count()} local)")

    mesh = create_mesh(MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1))
    print(f"[proc {proc}] mesh: {dict(mesh.shape)}")

    # per-host batch -> one global array (the train-loop data feed path)
    rng = np.random.default_rng(proc)
    local = rng.standard_normal((8, 1024)).astype(np.float32)
    xg = make_global_array(local, mesh, P(("replica", "fsdp"), None))

    w = jax.device_put(
        rng.standard_normal((1024, 1024)).astype(np.float32),
        NamedSharding(mesh, P(None, "tensor")),
    )
    y = jax.jit(lambda a, b: a @ b)(xg, w)
    jax.block_until_ready(y)
    print(f"[proc {proc}] matmul OK: {y.shape} {y.sharding}")
    if proc == 0:
        jax.debug.visualize_array_sharding(y)


if __name__ == "__main__":
    main()
