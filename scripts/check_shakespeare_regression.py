"""Golden-loss regression check on the synthetic shakespeare_char recipe.

The reference's correctness bar is trained curves
(/root/reference/README.md:55: shakespeare_char to ~1.47 val on the real
tinyshakespeare). This environment has zero egress, so the tracked stand-in
(VERDICT r2 Next #6) is the deterministic synthetic corpus: the full
5000-step shakespeare_char recipe must reach **val <= 0.75** (r2 measured
0.6995, r3 re-measured below; the margin covers seed/jitter). The
real-data golden commands stay documented in PARITY.md.

    PYTHONPATH=. python scripts/check_shakespeare_regression.py
        [--rundir=...] [--threshold=0.75]

Exit 0 iff the final val loss clears the threshold; writes the run under
artifacts/shakespeare_synth_check/ (metrics.jsonl + summary.json).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rundir", default=None)
    ap.add_argument("--threshold", type=float, default=0.75)
    ap.add_argument("--max_steps", type=int, default=5000)
    args = ap.parse_args()

    workdir = args.rundir or tempfile.mkdtemp(prefix="shk_synth_")
    cleanup = args.rundir is None
    data_dir = os.path.join(workdir, "data")
    rundir = os.path.join(workdir, "run")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    try:
        ok, summary = _run(args, workdir, data_dir, rundir, env)
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(summary))
    sys.exit(0 if ok else 1)


def _run(args, workdir, data_dir, rundir, env):
    subprocess.run(
        [sys.executable, os.path.join(REPO, "data/shakespeare_char/prepare.py"),
         "--synthetic", "--out_dir", data_dir],
        check=True, env=env,
    )
    subprocess.run(
        [sys.executable, os.path.join(REPO, "launch.py"),
         "--config=shakespeare_char", f"--rundir={rundir}",
         "--set", f"data_dir={data_dir}", f"max_steps={args.max_steps}",
         "ckpt_interval=100000"],
        check=True, env=env,
    )

    val = None
    with open(os.path.join(rundir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "loss/val" in rec:
                val = rec["loss/val"]
    if val is None:
        raise RuntimeError("run produced no val-loss points")

    ok = val <= args.threshold
    summary = {
        "final_val_loss": val,
        "threshold": args.threshold,
        "max_steps": args.max_steps,
        "ok": bool(ok),
    }
    outdir = os.path.join(REPO, "artifacts", "shakespeare_synth_check")
    os.makedirs(outdir, exist_ok=True)
    shutil.copy(os.path.join(rundir, "metrics.jsonl"), outdir)
    with open(os.path.join(outdir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    return ok, summary


if __name__ == "__main__":
    main()
