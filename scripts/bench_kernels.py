"""Kernel microbenchmarks on the current devices (run on real TPU).

    python scripts/bench_kernels.py [--iters 10]

Times each op chained inside ONE jit dispatch (lax.scan) so relay RTT and
dispatch overhead cancel (see PERF.md "Bench methodology"). Used to make
data-driven kernel choices — the fused-vs-jnp RMSNorm decision and the
flash block-size table in PERF.md come from this script.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp


def scan_time(fn, init, iters=10):
    @jax.jit
    def run(c):
        def body(c, _):
            return fn(c), None

        out, _ = jax.lax.scan(body, c, None, length=iters)
        return out

    jax.block_until_ready(run(init))
    t0 = time.perf_counter()
    jax.block_until_ready(run(init))
    return (time.perf_counter() - t0) / iters


def bench_rmsnorm(iters: int) -> None:
    from midgpt_tpu.ops.fused_norm import fused_rms_norm

    shapes = [(16, 1024, 768), (8, 1024, 2048)]
    for shape in shapes:
        x0 = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.bfloat16)

        def jnp_norm(x):
            out = x * jax.lax.rsqrt(
                jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-5
            )
            return out

        for name, f in (("jnp", jnp_norm), ("fused", lambda x: fused_rms_norm(x, None, 1e-5))):
            t = scan_time(lambda x, f=f: f(x).astype(jnp.bfloat16), x0, iters)
            g = jax.grad(lambda x, f=f: f(x).astype(jnp.float32).sum())
            tb = scan_time(lambda x, g=g: g(x).astype(jnp.bfloat16), x0, iters)
            print(
                f"rmsnorm {shape} {name:5s}: fwd {t*1e6:7.1f} us   "
                f"fwd+bwd {tb*1e6:7.1f} us"
            )


def bench_flash_blocks(iters: int) -> None:
    from midgpt_tpu.ops.flash import flash_attention

    b, h, t, c = 16, 12, 1024, 64
    kk = jax.random.normal(jax.random.PRNGKey(4), (b, h, t, c), jnp.bfloat16)
    vv = jax.random.normal(jax.random.PRNGKey(5), (b, h, t, c), jnp.bfloat16)
    q0 = jax.random.normal(jax.random.PRNGKey(6), (b, h, t, c), jnp.bfloat16)
    fl = 2 * 2 * b * h * t * t * c / 2
    for bs in (128, 256, 512, 1024):
        f = lambda q, bs=bs: flash_attention(
            q, kk, vv, causal=True, block_q=bs, block_k=bs
        ).astype(jnp.bfloat16)
        tf = scan_time(f, q0, iters)
        g = jax.grad(
            lambda q, bs=bs: flash_attention(
                q, kk, vv, causal=True, block_q=bs, block_k=bs
            ).astype(jnp.float32).sum()
        )
        tb = scan_time(lambda q, g=g: g(q).astype(jnp.bfloat16), q0, iters)
        print(
            f"flash blk {bs:4d}: fwd {tf*1e3:6.2f} ms ({fl/tf/1e12:5.1f} TF/s)  "
            f"fwd+dq {tb*1e3:6.2f} ms"
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    print(f"device: {jax.devices()[0].device_kind} x{jax.device_count()}")
    bench_rmsnorm(args.iters)
    bench_flash_blocks(args.iters)


if __name__ == "__main__":
    main()
