"""One-shot on-hardware validation for the transpose-free flash layout.

    python scripts/validate_bthc.py

Run this FIRST THING in a session with a live TPU (relay died before it
could run in r2 — see PERF.md). It:
 1. checks bthc-vs-bhtc fwd/bwd parity on the chip (Mosaic, not interpret);
 2. times both layouts at the 124M bench shape;
 3. prints the verdict: if bthc compiles and is faster, flip the default in
    midgpt_tpu/config.py (ModelConfig.attn_layout) and re-run bench.py.

Runs detached-friendly (no timeout-kill mid-RPC — PERF.md post-mortem).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    from midgpt_tpu.ops.flash import flash_attention

    b, h, t, c = 16, 12, 1024, 64
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, c), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, c), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, t, c), jnp.bfloat16)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    # 1. parity on hardware
    out_ref = jax.jit(lambda q, k, v: flash_attention(q, k, v))(q, k, v)
    out_t = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, layout="bthc")
    )(qt, kt, vt)
    diff = float(
        jnp.max(
            jnp.abs(
                jnp.transpose(out_t, (0, 2, 1, 3)).astype(jnp.float32)
                - out_ref.astype(jnp.float32)
            )
        )
    )
    print(f"fwd parity max|diff|: {diff:.2e}")
    assert diff < 1e-2, "bthc fwd mismatch on hardware"

    g_ref = jax.jit(
        jax.grad(lambda q: flash_attention(q, k, v).astype(jnp.float32).sum())
    )(q)
    g_t = jax.jit(
        jax.grad(
            lambda qt: flash_attention(qt, kt, vt, layout="bthc")
            .astype(jnp.float32)
            .sum()
        )
    )(qt)
    gdiff = float(
        jnp.max(
            jnp.abs(
                jnp.transpose(g_t, (0, 2, 1, 3)).astype(jnp.float32)
                - g_ref.astype(jnp.float32)
            )
        )
    )
    print(f"bwd parity max|diff|: {gdiff:.2e}")
    assert gdiff < 1e-2, "bthc bwd mismatch on hardware"

    # 2. timing (chained inside one dispatch)
    def scan_time(fn, init, iters=10):
        @jax.jit
        def run(x):
            def body(x, _):
                return fn(x), None

            out, _ = jax.lax.scan(body, x, None, length=iters)
            return out

        jax.block_until_ready(run(init))
        t0 = time.perf_counter()
        jax.block_until_ready(run(init))
        return (time.perf_counter() - t0) / iters

    t_ref = scan_time(
        lambda q: flash_attention(q, k, v).astype(jnp.bfloat16), q
    )
    t_t = scan_time(
        lambda qt: flash_attention(qt, kt, vt, layout="bthc").astype(
            jnp.bfloat16
        ),
        qt,
    )
    print(f"fwd bhtc {t_ref*1e3:.2f} ms   bthc {t_t*1e3:.2f} ms")
    print(
        "VERDICT: bthc OK on hardware — flip ModelConfig.attn_layout "
        "default to 'bthc' and re-run bench.py"
        if t_t <= t_ref * 1.05
        else "VERDICT: bthc compiles but is not faster in isolation; "
        "still worth a full bench.py A/B (the win is the removed "
        "transposes outside the kernel)"
    )


if __name__ == "__main__":
    main()
