"""Side-by-side loss parity vs the ACTUAL reference implementation.

VERDICT r3 Missing #1: "matches reference loss" was an inference, never a
measurement. This script runs BOTH frameworks on the identical synthetic
shakespeare-style token file, same hyperparameters, same step count, on
the 8-device CPU mesh, and asserts final-val agreement:

- reference: /root/reference's own ``src.train.train()`` loop, unmodified,
  via the minimal equinox shim (scripts/eqx_shim.py) and a wandb stub that
  records its logged loss series (the image has no equinox/wandb and zero
  egress). Reference: /root/reference/src/train.py:127-225.
- ours: midgpt_tpu.train.train() with the matching ModelConfig (init-only
  tied embeddings, QK-LN, GELU MLP, naive attention — the reference math).

Data order and init keys necessarily differ between frameworks (different
loader/RNG designs), so the assertion is on the CONVERGED final val loss,
not per-step curves. Writes artifacts/reference_parity.json with both
series.

    python scripts/check_reference_parity.py [--steps 600] [--tol 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import types

# respect an explicitly-set XLA_FLAGS (the parent sets 8 virtual devices
# for the reference child and single-device for ours); default to 8
if os.environ.get("XLA_FLAGS") is None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# --platform=tpu leaves the site default backend (the real chip) in
# place for the --full on-chip parity run; anything else pins CPU (the
# historical behavior — JAX_PLATFORMS in the env is ignored on this
# host, so the pin must happen in-process before backend init)
def _sniff_platform() -> str:
    for i, a in enumerate(sys.argv):
        if a == "--platform" and i + 1 < len(sys.argv):
            return sys.argv[i + 1]
        if a.startswith("--platform="):
            return a.split("=", 1)[1]
    return "cpu"


_PLATFORM = _sniff_platform()
if _PLATFORM == "cpu":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

# shrunken-but-faithful shakespeare_char family shape (the full 6x384
# config runs hours on CPU; both sides get the identical shrink). --full
# switches to the REAL shakespeare_char recipe (L6/H6/D384/T256, dropout
# 0.2, reference src/configs/shakespeare_char.py) — ~6 min/side on one
# TPU chip, with ours on the production kernel path (VERDICT r4 Next #3).
MODEL = dict(block_size=256, vocab_size=65, n_layer=4, n_head=6, n_embd=192)
HPARAMS = dict(
    learning_rate=1e-3, min_lr=1e-4, beta2=0.99, weight_decay=1e-4,
    batch_size=32, g_accum_iters=1,
)
MODEL_FULL = dict(block_size=256, vocab_size=65, n_layer=6, n_head=6, n_embd=384)
HPARAMS_FULL = dict(
    learning_rate=1e-3, min_lr=1e-4, beta2=0.99, weight_decay=1e-4,
    batch_size=64, g_accum_iters=1,
)
DROPOUT = 0.0  # --full sets 0.2 (the reference recipe); the two sides
# draw different dropout streams (jax.random vs counter hash), so full-
# config parity is FINAL-VAL agreement at a tolerance, not per-step
OURS_IMPL = "naive"  # --full sets "auto": fused attention + flash dropout


def _prepare_data(outdir: str) -> str:
    """Identical synthetic token file for both frameworks."""
    sys.path.insert(0, os.path.join(REPO, "data", "shakespeare_char"))
    import prepare as prep  # noqa

    datadir = os.path.join(outdir, "data")
    os.makedirs(datadir, exist_ok=True)
    argv, sys.argv = sys.argv, ["prepare.py", "--synthetic", "--out_dir", datadir]
    try:
        prep.main()
    finally:
        sys.argv = argv
    return datadir


def run_reference(datadir: str, steps: int, eval_interval: int,
                  debug: bool = False) -> dict:
    """Run /root/reference's train() via the equinox shim; returns the
    loss series its loop logs to (stubbed) wandb."""
    from eqx_shim import make_equinox_module

    if _PLATFORM == "tpu":
        # the reference hardcodes an (n_devices//8, 8) mesh
        # (src/train.py:129-130) and cannot see one chip; stub the mesh
        # FACTORY to a 1-device (1, 1) mesh — a driver-side shim like the
        # equinox/wandb stubs, the reference code itself stays untouched.
        # P(None, ('replica','data'), None) over one device is a no-op.
        from jax.experimental import mesh_utils

        def _one_device_mesh(shape, *a, **k):
            return np.asarray(jax.devices()[:1]).reshape((1, 1))

        mesh_utils.create_device_mesh = _one_device_mesh

    logged: dict = {"train": [], "val": [], "opt": []}
    wandb = types.ModuleType("wandb")

    def _log(d, step=None):
        if "loss/train" in d:
            logged["train"].append((step, float(d["loss/train"])))
            logged["val"].append((step, float(d["loss/val"])))
        if "loss/optimized" in d:
            logged["opt"].append((step, float(d["loss/optimized"])))

    wandb.log = _log
    wandb.finish = lambda *a, **k: None
    wandb.init = lambda *a, **k: None

    sys.modules["equinox"] = make_equinox_module()
    sys.modules["wandb"] = wandb
    if not hasattr(jax, "tree_map"):  # removed in newer jax; reference uses it
        jax.tree_map = jax.tree.map
    sys.path.insert(0, REFERENCE)
    from src.model import GPTConfig
    from src.train import ExperimentConfig, train

    rundir = tempfile.mkdtemp(prefix="ref_parity_")
    cfg = ExperimentConfig(
        rundir=rundir,
        data_dir=datadir,
        warmup_steps=max(1, steps // 10),
        lr_decay_steps=steps,
        max_steps=steps,
        eval_interval=eval_interval,
        param_dtype="float32",
        compute_dtype="bfloat16",
        shard_model=False,
        model_config=GPTConfig(dropout=DROPOUT, **MODEL),
        debug=debug,  # smoke mode: 1-batch evals, no checkpointing
        **HPARAMS,
    )
    np.random.seed(0)  # the reference's get_batch uses global numpy RNG
    train(cfg)
    return logged


def run_ours(datadir: str, steps: int, eval_interval: int,
             debug: bool = False) -> dict:
    from midgpt_tpu.config import (
        ExperimentConfig, MeshConfig, ModelConfig,
    )
    from midgpt_tpu.train import train

    rundir = tempfile.mkdtemp(prefix="ours_parity_")
    cfg = ExperimentConfig(
        model=ModelConfig(
            dropout=DROPOUT, attn_impl=OURS_IMPL,
            remat="none" if OURS_IMPL == "auto" else "full",
            scan_unroll=MODEL["n_layer"] if OURS_IMPL == "auto" else 1,
            qk_norm=True, tie_embeddings=False, mlp="gelu", **MODEL,
        ),
        data_dir=datadir,
        rundir=rundir,
        warmup_steps=max(1, steps // 10),
        lr_decay_steps=steps,
        max_steps=steps,
        eval_interval=eval_interval,
        eval_batches=1 if debug else 200,  # the reference's evaluate() uses 200
        # fsdp=-1 -> all visible devices (the parent runs this side
        # single-device: same math, no CPU collective rendezvous)
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
        **HPARAMS,
    )
    final = train(cfg)
    series = []
    with open(os.path.join(rundir, "metrics.jsonl")) as f:
        for line in f:
            series.append(json.loads(line))
    return {"final": final, "series": series}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--eval_interval", type=int, default=200)
    ap.add_argument("--tol", type=float, default=0.1,
                    help="max |final val loss difference| in nats")
    ap.add_argument("--side", choices=("ref", "ours", "both"), default="both")
    ap.add_argument("--debug", action="store_true",
                    help="smoke mode: 1-batch evals, no reference ckpts")
    ap.add_argument("--datadir", default=None)
    ap.add_argument("--platform", choices=("cpu", "tpu"), default="cpu",
                    help="tpu = leave the real-chip backend in place "
                    "(consumed before argparse; listed here for --help)")
    ap.add_argument("--full", action="store_true",
                    help="real shakespeare_char recipe (L6/D384, dropout "
                    "0.2, batch 64) with ours on the auto kernel path; "
                    "pass --steps 5000 for the full run")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    assert args.platform == _PLATFORM, (
        f"--platform sniffed as {_PLATFORM!r} before backend init but "
        f"argparse saw {args.platform!r}"
    )
    if args.full:
        global MODEL, HPARAMS, DROPOUT, OURS_IMPL
        MODEL, HPARAMS = MODEL_FULL, HPARAMS_FULL
        DROPOUT, OURS_IMPL = 0.2, "auto"

    if args.side != "both":
        # child mode: run one side, dump its series as JSON
        result = (
            run_reference if args.side == "ref" else run_ours
        )(args.datadir, args.steps, args.eval_interval, debug=args.debug)
        with open(args.out, "w") as f:
            json.dump(result, f)
        return

    # parent: one subprocess per side. This box exposes ONE physical core;
    # the reference needs its 8-virtual-device mesh (its train() hardcodes
    # an (n//8, 8) mesh), but running both sides plus 8-thread CPU
    # collective rendezvous in one contended process deadlocks XLA's
    # 40s rendezvous timeout. Ours runs single-device (identical math).
    import subprocess

    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    datadir = _prepare_data(tempfile.mkdtemp(prefix="parity_data_"))

    results = {}
    for side, flags in (("ref", "--xla_force_host_platform_device_count=8"),
                        ("ours", "")):
        out = tempfile.mktemp(suffix=f"_{side}.json")
        env = dict(os.environ)
        if _PLATFORM == "tpu":
            env.pop("XLA_FLAGS", None)  # real chip: no virtual devices
        else:
            env["XLA_FLAGS"] = flags
            env["PALLAS_AXON_POOL_IPS"] = ""  # keep jax off the TPU relay
        cmd = [sys.executable, os.path.abspath(__file__),
               "--side", side, "--datadir", datadir, "--out", out,
               "--steps", str(args.steps),
               "--eval_interval", str(args.eval_interval),
               f"--platform={_PLATFORM}"]
        if args.full:
            cmd.append("--full")
        if args.debug:
            cmd.append("--debug")
        print(f"[parity] running {side} ...", flush=True)
        subprocess.run(cmd, check=True, env=env)
        with open(out) as f:
            results[side] = json.load(f)

    ref, ours = results["ref"], results["ours"]
    ref_val = ref["val"][-1][1]
    our_val = float(ours["final"]["val_loss"])
    record = {
        "model": MODEL,
        "hparams": HPARAMS,
        "steps": args.steps,
        "reference": ref,
        "ours_final": ours["final"],
        "ours_series": ours["series"],
        "ref_final_val": ref_val,
        "our_final_val": our_val,
        "abs_diff": abs(ref_val - our_val),
        "tol": args.tol,
    }
    with open(os.path.join(outdir, "reference_parity.json"), "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: record[k] for k in
                      ("ref_final_val", "our_final_val", "abs_diff", "tol")}))
    assert abs(ref_val - our_val) <= args.tol, (
        f"final val loss diverged: reference {ref_val:.4f} vs ours "
        f"{our_val:.4f} (tol {args.tol})"
    )
    print("PARITY OK")


if __name__ == "__main__":
    main()
