"""Distributed checkpoint smoke (parity: reference scripts/test_ckpt.py:8-24).

Run on every host of a slice:

    python scripts/smoke_ckpt.py --rundir=gs://bucket/path [--multihost]

Saves a sharded TrainState through the framework's async Checkpointer,
restores it, and verifies round-trip equality.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rundir", required=True)
    ap.add_argument("--multihost", action="store_true")
    args = ap.parse_args()
    if args.multihost:
        jax.distributed.initialize()

    from midgpt_tpu.checkpoint import Checkpointer
    from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.train import init_state, make_optimizer

    cfg = ExperimentConfig(
        model=ModelConfig(
            block_size=128, vocab_size=256, n_layer=2, n_head=4, n_embd=128,
        ),
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
    )
    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))

    ckpt = Checkpointer(args.rundir, keep=1, save_interval_steps=1)
    ckpt.save(
        0,
        {"params": state.params, "opt_state": state.opt_state},
        meta={"step": 0, "smoke": True},
        force=True,
    )
    ckpt.wait()

    items, meta = ckpt.restore({"params": state.params})
    np.testing.assert_allclose(
        np.asarray(jax.device_get(items["params"].wte.weight)),
        np.asarray(jax.device_get(state.params.wte.weight)),
    )
    ckpt.close()
    if jax.process_index() == 0:
        print(f"checkpoint round-trip OK (meta: {meta})")


if __name__ == "__main__":
    main()
