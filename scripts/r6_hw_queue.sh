#!/bin/bash
# r6 hardware measurement queue: poll the wedged relay; on recovery run
# every queued measurement in sequence. Obeys PERF.md relay rules — the
# probe is DETACHED and never timeout-killed (killing TPU clients
# mid-RPC is what wedged the relay in r4): one probe hangs harmlessly
# until the relay recovers, then writes a sentinel the shell polls.
#
# Re-arm on session start (VERDICT r5 Next #1):
#   nohup bash scripts/r6_hw_queue.sh >/dev/null 2>&1 &
#   pgrep -f r6_hw_queue   # verify it is polling
cd /root/repo
LOG=artifacts/r6
mkdir -p "$LOG"
SENT=/tmp/r6_probe_ok
rm -f "$SENT"

probe() {
  # pin the probe to the TPU backend: on a CPU-only box jax would
  # otherwise fall back to CPU, "succeed", and start the whole TPU
  # pipeline on the host CPU. Pinned, a no-TPU probe exits nonzero
  # (respawned every poll until hardware appears) and a wedged relay
  # hangs it harmlessly, exactly as before.
  nohup env JAX_PLATFORMS=tpu python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256,256), jnp.bfloat16)
float((x@x)[0,0])
open('$SENT','w').write('1')" > /dev/null 2>&1 &
  PROBE_PID=$!
}

echo "[queue] $(date -u +%H:%M:%S) polling relay (detached probe)" >> "$LOG/queue.log"
probe
while true; do
  sleep 120
  [ -f "$SENT" ] && break
  if ! kill -0 "$PROBE_PID" 2>/dev/null; then
    probe  # previous probe EXITED (clean error) without sentinel: respawn
  fi     # still running = hanging on the wedge: keep waiting on it
done
echo "[queue] $(date -u +%H:%M:%S) relay RECOVERED - starting pipeline" >> "$LOG/queue.log"

run() {  # run <name> <cmd...>: sequential, logged, never under timeout
  echo "[queue] $(date -u +%H:%M:%S) start $1" >> "$LOG/queue.log"
  shift_name=$1; shift
  "$@" > "$LOG/$shift_name.log" 2>&1
  echo "[queue] $(date -u +%H:%M:%S) done $shift_name rc=$?" >> "$LOG/queue.log"
}

run bench1 python bench.py
run decode python scripts/bench_decode.py
# NEW in r6: the continuous-batching serving bench (paged KV + fused
# K-step decode dispatch) — tok/s, TTFT p50/p99, occupancy, dispatch
# count at the 124M shape under a Poisson mix; writes
# artifacts/bench_serving.json. A K-ladder probes the dispatch-latency
# amortization the subsystem exists for. Telemetry is ON by default on
# every serving rung (tracing never touches the compiled programs —
# greedy streams are bitwise on/off, serving.telemetry), so each row
# carries serve_tbt_* / serve_queue_delay_* percentiles; rungs with
# --timeline_dir additionally persist a Perfetto-loadable per-request
# timeline + the metrics-registry snapshot — so even a wedged run
# leaves a dispatch-level timeline (the bench watchdog dumps the flight
# recorder in-band to the row on a trip).
run serving python scripts/bench_serving.py --platform=tpu \
  --timeline_dir artifacts/r6/tl_serving
run serving_k1 python scripts/bench_serving.py --platform=tpu --window 1 \
  --out artifacts/bench_serving_k1.json
run serving_k16 python scripts/bench_serving.py --platform=tpu --window 16 \
  --out artifacts/bench_serving_k16.json
# Prefix-cache ladder on a shared-system-prompt mix (the traffic shape
# the cache exists for): identical trace with the cache off vs on —
# serve_prefix_hit_rate / serve_prefill_tokens_saved quantify the
# prefill FLOPs skipped, tok_s and TTFT the end-to-end win. The third
# rung adds Sarathi-style chunked prefill (128-token chunks) to bound
# TTFT p99 under the long shared prompts.
run serving_sys_nocache python scripts/bench_serving.py --platform=tpu \
  --sys_prompt_len 256 --max_prompt 128 --prefix_cache off \
  --out artifacts/bench_serving_sys_nocache.json
run serving_sys_cache python scripts/bench_serving.py --platform=tpu \
  --sys_prompt_len 256 --max_prompt 128 \
  --out artifacts/bench_serving_sys_cache.json
run serving_sys_chunked python scripts/bench_serving.py --platform=tpu \
  --sys_prompt_len 256 --max_prompt 128 --prefill_chunk 128 \
  --out artifacts/bench_serving_sys_chunked.json
# Self-speculative decoding ladder (PR 5) on a repetitive-text mix (the
# workload n-gram drafting targets): identical trace with speculation
# off vs on — serve_tokens_per_dispatch and serve_spec_acceptance_rate
# quantify tokens-per-forward, serve_tok_s the end-to-end win. The
# window-1 off rung is the one-token-per-forward baseline the PERF.md
# speedup arithmetic is stated against.
run serving_spec_base python scripts/bench_serving.py --platform=tpu \
  --repetitive --window 1 --spec off \
  --out artifacts/bench_serving_spec_base.json
run serving_spec_off python scripts/bench_serving.py --platform=tpu \
  --repetitive --window 8 --spec off \
  --out artifacts/bench_serving_spec_off.json
run serving_spec_on python scripts/bench_serving.py --platform=tpu \
  --repetitive --spec on --spec_len 8 \
  --out artifacts/bench_serving_spec_on.json
# Sampled speculation rung pair (rejection-sampling verify, this PR):
# the SAME repetitive trace at temperature 0.8 / top_k 20 with spec off
# vs on, at the production serving precision (int8 weights + int8 KV) —
# the sampled-chat traffic shape the greedy-only assert used to lock
# out. serve_spec_acceptance_rate is the measured accept fraction of
# the rejection sampler and serve_tokens_per_dispatch the headline;
# PERF.md's E[accepted]+1 arithmetic is stated against this pair.
run serving_spec_sampled_off python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on \
  --repetitive --window 8 --spec off --temperature 0.8 --top_k 20 \
  --out artifacts/bench_serving_spec_sampled_off.json
run serving_spec_sampled_on python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on \
  --repetitive --spec on --spec_len 8 --temperature 0.8 --top_k 20 \
  --out artifacts/bench_serving_spec_sampled_on.json
# Int8 quantized weight path (PR 6): identical trace with the bf16 vs
# int8 weight stream — serve_tok_s measures the halved-weight-stream
# floor move (~0.43 -> ~0.27 ms/step at 124M B=8 per PERF.md's roofline
# arithmetic; target measured ms/tok toward ~0.6), and
# serve_peak_hbm_bytes shows the residency win. The bf16 rung reuses
# artifacts/bench_serving.json (the default-run rung above).
run serving_quant python scripts/bench_serving.py --platform=tpu \
  --quant on --out artifacts/bench_serving_quant.json
# TPxDP sharded serving (PR 7): the same trace on a tp=4 engine (model
# weights + KV pool split over 4 chips — the SNIPPETS.md target
# geometry; serve_comms_by_axis records the per-dispatch psum bytes the
# PERF.md arithmetic predicts) and on 2 shared-nothing tp=2 replicas
# under least-loaded admission (throughput axis). Skips cleanly (rc!=0
# in queue.log) on hosts with fewer than 4 chips.
run serving_tp4 python scripts/bench_serving.py --platform=tpu \
  --tp 4 --out artifacts/bench_serving_tp4.json
run serving_tp2_dp2 python scripts/bench_serving.py --platform=tpu \
  --tp 2 --dp_replicas 2 --out artifacts/bench_serving_tp2_dp2.json
run serving_tp4_quant python scripts/bench_serving.py --platform=tpu \
  --tp 4 --quant on --out artifacts/bench_serving_tp4_quant.json
# Pallas ragged paged-attention kernel + int8 KV pool (PR 9): the same
# B=8 trace across the 2x2 (kernel x kv-quant) cell grid, int8 weights
# throughout (the production serving precision). The kernel removes the
# XLA page-gather intermediate (the K+V stream crosses HBM once instead
# of ~3x), kv-quant halves the bytes themselves: PERF.md's corrected
# decomposition puts the int8-weights floor at ~0.39 ms/step with bf16
# KV (0.155 w + 0.236 kv) and ~0.27 with int8 KV (0.155 + 0.118) — the
# realized ms/tok of each cell lands next to those static floors
# (serve_hbm_floor_ms_static is recorded in-band per rung).
run serving_kernel_off_kvq_off python scripts/bench_serving.py \
  --platform=tpu --quant on --paged_kernel xla --kv_quant off \
  --out artifacts/bench_serving_kernel_off_kvq_off.json
run serving_kernel_on_kvq_off python scripts/bench_serving.py \
  --platform=tpu --quant on --paged_kernel pallas --kv_quant off \
  --out artifacts/bench_serving_kernel_on_kvq_off.json
run serving_kernel_off_kvq_on python scripts/bench_serving.py \
  --platform=tpu --quant on --paged_kernel xla --kv_quant on \
  --out artifacts/bench_serving_kernel_off_kvq_on.json
run serving_kernel_on_kvq_on python scripts/bench_serving.py \
  --platform=tpu --quant on --paged_kernel pallas --kv_quant on \
  --out artifacts/bench_serving_kernel_on_kvq_on.json
# NEW in PR 11: the fused-layer-scan rung pair (ROADMAP item 1's
# success metric, measured directly): fused vs unfolded decode at the
# production precision (int8 weights + int8 KV), single chip and tp=2.
# The fold is BITWISE the unrolled program (analysis.fusion prover +
# token-identity matrix); the delta between each pair is pure per-layer
# launch overhead — the residual PERF.md's decomposition puts between
# r5's 0.905 ms/tok and the 0.278/0.139 ms HBM floors. Each record
# carries its static structure in-band (serve_static_launches_per_window
# / serve_static_inlined_layer_bodies / serve_static_layer_scan_length).
# The fused rung pair carries full timelines (PR 12 telemetry): the
# per-dispatch lanes in the Perfetto trace + the dispatch_s histogram
# in metrics_snapshot.json give the fused-vs-unfused comparison its
# dispatch-level timing breakdown, not just the ms/tok headline.
run serving_fuse_off_tp1 python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on --layer_scan off \
  --timeline_dir artifacts/r6/tl_fuse_off_tp1 \
  --out artifacts/bench_serving_fuse_off_tp1.json
run serving_fuse_on_tp1 python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on --layer_scan on \
  --timeline_dir artifacts/r6/tl_fuse_on_tp1 \
  --out artifacts/bench_serving_fuse_on_tp1.json
run serving_fuse_off_tp2 python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on --layer_scan off --tp 2 \
  --timeline_dir artifacts/r6/tl_fuse_off_tp2 \
  --out artifacts/bench_serving_fuse_off_tp2.json
run serving_fuse_on_tp2 python scripts/bench_serving.py \
  --platform=tpu --quant on --kv_quant on --layer_scan on --tp 2 \
  --timeline_dir artifacts/r6/tl_fuse_on_tp2 \
  --out artifacts/bench_serving_fuse_on_tp2.json
# Tracing-overhead rung (PERF.md target: <2% on, unmeasurable off):
# the headline trace re-run with --telemetry off — the delta vs the
# default rung above IS the measured tracing overhead on hardware.
run serving_tele_off python scripts/bench_serving.py --platform=tpu \
  --telemetry off --out artifacts/bench_serving_tele_off.json
# NEW in PR 13: the SLO trace rung (serving.frontdoor) — goodput-under-
# SLO on hardware, the metric the Gemma-on-TPU serving comparison
# (PAPERS.md) ranks systems by. Bursty arrivals through the async front
# door, a 4-tenant shared-prefix mix, 3 priority levels, a 2 s + 20 ms/
# token e2e SLO, and 10% client cancellations: the row's headline pair
# is serve_tok_s (work done) vs serve_goodput_slo_tok_s (work banked),
# with serve_deadline_met/missed/shed and serve_cancelled explaining
# the gap, and the timeline showing the priority/deadline scheduling
# at dispatch granularity.
run serving_slo_trace python scripts/bench_serving.py --platform=tpu \
  --trace bursty --slo_ms 2000 --slo_per_token_ms 20 \
  --priority_levels 3 --cancel_frac 0.1 \
  --tenants 4 --sys_prompt_len 128 --max_prompt 128 \
  --timeline_dir artifacts/r6/tl_slo_trace \
  --out artifacts/bench_serving_slo_trace.json
# NEW in PR 18: disaggregated prefill/decode + prefix-affinity routing
# (serving.cluster). Rung pair 1 — the affinity A/B on the zipf-tenant
# shared-prefix trace: identical seed-pinned workload over 2 replicas,
# routing off vs on. Headline delta is serve_prefix_hit_rate (affinity
# must land strictly higher at equal serve_tokens_generated — routing
# never changes tokens), with serve_prefix_affinity_hits /
# serve_routed_fallback explaining the admission mix. Trace-mode
# arrivals interleave with scheduler steps, so the router probes LIVE
# resident state (an open-loop submit-everything drive would see empty
# caches and fall back on every request).
run serving_affinity_off python scripts/bench_serving.py --platform=tpu \
  --dp_replicas 2 --trace poisson --tenants 4 --sys_prompt_len 128 \
  --max_prompt 128 --affinity off \
  --out artifacts/bench_serving_affinity_off.json
run serving_affinity_on python scripts/bench_serving.py --platform=tpu \
  --dp_replicas 2 --trace poisson --tenants 4 --sys_prompt_len 128 \
  --max_prompt 128 --affinity on \
  --out artifacts/bench_serving_affinity_on.json
# Rung pair 2 — disagg 2+2 vs the chip-equal monolithic baseline (4
# single-chip replicas either way): the row's headline is
# serve_ttft_by_class — the compute-bound prefill pool's TTFT
# distribution vs the dp=4 row's mixed one (PERF.md predicts the win
# from the prefill-vs-decode roofline split) — next to
# serve_handoff_count/bytes pricing the page movement, with the
# timeline showing handoff spans on the prefill replicas' lanes.
run serving_disagg_2p2 python scripts/bench_serving.py --platform=tpu \
  --disagg 2+2 --timeline_dir artifacts/r6/tl_disagg \
  --out artifacts/bench_serving_disagg_2p2.json
run serving_mono_dp4 python scripts/bench_serving.py --platform=tpu \
  --dp_replicas 4 \
  --out artifacts/bench_serving_mono_dp4.json
# NEW in PR 19: long-context serving. Rung pair 1 — the 100k-token
# long-document preset (--prompt_len pins every prompt and widens the
# model to hold the context) at tp=2, sequence-parallel prefill off vs
# on over the identical trace: the headline is serve_ttft_long_p99
# against the serve_prefill_floor_ms_static /
# serve_prefill_sp_floor_ms_static bracket (Megatron-SP shards the
# per-token segments TP replicates — embedding, layernorms, residual
# adds — over 'tensor'; streams are bitwise identical either way, so
# the TTFT delta is pure replicated-row work + activation traffic).
# slots=2 keeps the default pool (~7.4 GB of pages, split over the 2
# chips) inside HBM at this context.
run serving_longctx_sp_off python scripts/bench_serving.py --platform=tpu \
  --tp 2 --prompt_len 100000 --prefill_chunk 512 --requests 4 --slots 2 \
  --rate 0.05 --prefill_sp off \
  --out artifacts/bench_serving_longctx_sp_off.json
run serving_longctx_sp_on python scripts/bench_serving.py --platform=tpu \
  --tp 2 --prompt_len 100000 --prefill_chunk 512 --requests 4 --slots 2 \
  --rate 0.05 --prefill_sp on \
  --out artifacts/bench_serving_longctx_sp_on.json
# Spill-pressure rung: the same long-document trace against a pool
# sized BELOW the 2-slot working set (lifetime ~6258 pages/request) —
# cold chains spill to host RAM in LRU order instead of being
# discarded. serve_spilled_pages / serve_spill_faultback_pages price
# the host round-trips, serve_spill_resident_pages the host-side
# cache the pool gained, and status=ok with zero shed requests is the
# no-wedge acceptance measured on hardware.
run serving_longctx_spill python scripts/bench_serving.py --platform=tpu \
  --tp 2 --prompt_len 100000 --prefill_chunk 512 --requests 4 --slots 2 \
  --rate 0.05 --spill on --num_pages 7000 \
  --out artifacts/bench_serving_longctx_spill.json
# NEW in PR 20: long-context DECODE. Rung pair 2 — the same 100k-token
# preset made decode-heavy (long generations, int8 weights + int8 KV:
# the production precision whose thin pool stream makes the gather
# path's 3x KV re-read starkest) at tp=2, XLA gather fallback vs the
# banded Pallas kernel over the identical trace. The headline is
# serve_ms_per_tok against serve_floor_ms_per_tok_static: the banded
# kernel streams each resident K/V byte ONCE per pass where the
# gather path pays the [S, Pmax, Hkv, C, PS] HBM intermediate ~3x per
# step (PERF.md PR 20 arithmetic) — streams are bitwise identical, so
# the delta is pure traffic. serve_paged_kernel vs
# serve_paged_kernel_resolved proves the pallas row really ran the
# kernel (auto would resolve to it too; pinning both legs keeps the
# pair self-interpreting), and the timelines show the decode-lane
# dispatch cadence the kernel tightens.
run serving_longctx_decode_xla python scripts/bench_serving.py --platform=tpu \
  --tp 2 --prompt_len 100000 --prefill_chunk 512 --requests 4 --slots 2 \
  --rate 0.05 --min_new 256 --max_new 512 --quant on --kv_quant on \
  --paged_kernel xla --timeline_dir artifacts/r6/tl_longctx_decode_xla \
  --out artifacts/bench_serving_longctx_decode_xla.json
run serving_longctx_decode_pallas python scripts/bench_serving.py --platform=tpu \
  --tp 2 --prompt_len 100000 --prefill_chunk 512 --requests 4 --slots 2 \
  --rate 0.05 --min_new 256 --max_new 512 --quant on --kv_quant on \
  --paged_kernel pallas --timeline_dir artifacts/r6/tl_longctx_decode_pallas \
  --out artifacts/bench_serving_longctx_decode_pallas.json
run xl_l6_u3 python - << 'PYEOF'
# ONE cautious attempt to recover the L6-class XL headline: the full-
# unroll L6/B20 program crashes the remote compile helper (PERF.md r5);
# unroll=3 halves the program size with most of the unroll win (the DUS
# stacking cost scales with scan iteration count). If this 500s, do NOT
# retry — repeated submissions preceded today's wedge.
import sys
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
import bench
from midgpt_tpu.utils.metrics import mfu
try:
    cfg, state, chain, mk = bench._run_config(
        "none", 20, base="openwebtext_xl", n_layer=6, loss_chunk=512, unroll=3)
    tps, step_ms, state, mode = bench._rung_measure(cfg, state, chain, mk)
    print({"xl_l6_unroll3_mfu": round(mfu(tps, cfg.model, 1), 4),
           "step_ms": round(step_ms, 1), "measure": mode})
except Exception as e:
    print("L6/B20 unroll3 FAILED:", repr(e)[:300])
PYEOF
run parity_full python scripts/check_reference_parity.py --full --steps 5000 --eval_interval 1000 --platform=tpu --tol 0.06
run profile124 python scripts/profile_step.py --config=openwebtext --outdir=artifacts/r6/prof124 --batch 24 --set 'model.remat="none"' 'model.scan_unroll=12' 'model.attn_impl="auto"' loss_chunk=256 loss_chunk_unroll=true 'mesh.fsdp=1' 'mesh.tensor=1'
run moe_probe python - << 'PYEOF'
# opportunistic: 124M-family MoE throughput on one chip (experts
# unsharded; measures the dense-dispatch overhead vs the dense MLP rung)
import sys
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
import bench
from midgpt_tpu.utils.metrics import mfu
try:
    cfg, state, chain, mk = bench._run_config("none", 16, base="openwebtext_moe")
    tps, step_ms, state, mode = bench._rung_measure(cfg, state, chain, mk)
    print({"moe124_8e_tokens_per_sec": round(tps, 1), "step_ms": round(step_ms, 1),
           "measure": mode})
except Exception as e:
    print("moe probe FAILED:", repr(e)[:300])
PYEOF
# perf-trajectory ledger over this round's records (analysis/ledger.py,
# PR 15): gate EVERY rung row that landed under artifacts/r6 against
# the BENCH_r*.json trajectory (each row passed via --record — records
# ingested only as --records-dir would join the reference side and the
# self-check mode gates just the newest one). Hardware rows, so
# wall-clock bands gate HARD; the report rides next to the rung
# records. Non-fatal to the queue (the rows are already on disk either
# way) but the rc lands in the log so the driver sees a regression
# verdict in-band.
LEDGER_RECORDS=""
for f in artifacts/r6/*.json; do
  [ -f "$f" ] && LEDGER_RECORDS="$LEDGER_RECORDS --record $f"
done
run perf_ledger python -m midgpt_tpu.analysis --ledger \
    $LEDGER_RECORDS --hardware on \
    --report artifacts/r6/ledger_report.md

echo "[queue] $(date -u +%H:%M:%S) ALL DONE" >> "$LOG/queue.log"
