# makes scripts/ importable so bench.py can reuse bench_decode.measure_decode
