"""Serving bench: prefill + KV-cached decode throughput at the 124M shape.

Measures on the real chip (random-init weights — throughput only):
  prefill_tok_s        tokens/s through prefill (B=8, P=512)
  decode_tok_s         KV-cached in-window decode tokens/s (256 steps)
  decode_ms_per_tok    per-token latency of the same
  slide_kv_tok_s       past-window decode, ring-buffer KV mode
  slide_exact_tok_s    past-window decode, reference-parity recompute mode

The KV-cached decode path is a flagship redesign claim (the reference
re-runs the full forward per token, /root/reference/sample.py:68-95);
these are its numbers (VERDICT r2 Next #5). Writes
artifacts/bench_decode.json and prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp


def _sync(out):
    return int(jnp.sum(jax.tree.leaves(out)[0]))


def _timed(fn, *args, n=4):
    """Chained-delta timing: block_until_ready is unreliable under the axon
    relay (bench.py methodology note) — a forced host read is the only hard
    sync, and the (1 call) vs (n calls) delta cancels the RTT."""
    _sync(fn(*args))  # compile + hard sync
    t0 = time.perf_counter()
    _sync(fn(*args))
    t1 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn(*args)
    _sync(out)
    t2 = time.perf_counter()
    return max(1e-9, ((t2 - t1) - (t1 - t0)) / (n - 1))


def measure_decode(include_sliding: bool = False) -> dict:
    """Prefill + KV-decode throughput keys (``decode_*``) at the 124M
    shape; with ``include_sliding`` also the past-window modes (two extra
    heavy compiles — the standalone script runs them, bench.py doesn't)."""
    from midgpt_tpu.config import get_config
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.sampling import make_sampler

    cfg = get_config("openwebtext").model
    cfg = dataclasses.replace(cfg, attn_impl="auto")
    model = cast_floating(GPT.init(jax.random.PRNGKey(0), cfg), jnp.bfloat16)

    b, p = 8, 512
    key = jax.random.PRNGKey(1)
    prompt = jax.random.randint(key, (b, p), 0, cfg.vocab_size)

    # prefill timed on its FULL output (logits AND cache): returning only
    # logits lets XLA dead-code the ~150 MB of KV-cache writes, and a
    # max_new_tokens=0 sampler loses the whole forward (measured 6M "tok/s")
    from midgpt_tpu.models.gpt import KVCache, prefill

    cache = KVCache.init(cfg, b, p, dtype=jnp.bfloat16)
    # jit outputs are fully materialized regardless of which leaf the host
    # reads, so timing jit(prefill) on its full (logits, cache) output
    # through the shared _timed helper is sufficient
    t_prefill = _timed(jax.jit(prefill), model, prompt, cache)
    # decode rate = delta between two samplers (prefill cost cancels)
    n_dec = 256
    t_one = _timed(make_sampler(1, temperature=1.0), model, prompt, key)
    t_full = _timed(make_sampler(1 + n_dec, temperature=1.0), model, prompt, key)
    dec_per_tok = max(1e-9, (t_full - t_one) / n_dec)

    # HBM roofline for one decode step (all B tokens): stream every param
    # once (batched matvecs amortize over B) + stream the live KV slots of
    # all layers once (scores read K, value-sum reads V — both touched).
    # Measured rd+wr bandwidth on this chip class ~820 GB/s (PERF.md r5
    # probe); use 800 as the denominator so the floor is conservative.
    from midgpt_tpu.models.gpt import count_params

    param_bytes = count_params(model) * 2  # bf16 stream
    # in-window phase averages W/2 live slots; use the mean over the
    # measured 256-step window starting at p
    live_slots = min(p + n_dec / 2, cfg.block_size)
    kv_bytes = (
        cfg.n_layer * b * cfg.kv_heads * live_slots * cfg.head_dim * 2 * 2
    )
    floor_ms = (param_bytes + kv_bytes) / 800e9 * 1e3
    record = {
        "decode_shape": "124M B=8 T=1024 bf16",
        "decode_prefill_tok_s": round(b * p / t_prefill, 1),
        "decode_tok_s": round(b / dec_per_tok, 1),
        "decode_ms_per_tok": round(dec_per_tok * 1e3, 3),
        "decode_hbm_floor_ms": round(floor_ms, 3),
        "decode_vs_floor": round(dec_per_tok * 1e3 / floor_ms, 2),
    }
    if include_sliding:
        # past-window sliding: full-window prompt; per-token rate from the
        # mode-matched delta between 1-step and (1+n)-step samplers (same
        # pattern as the in-window block — the baseline's one step and the
        # prefill cost cancel exactly)
        n_slide = 64
        prompt_w = jax.random.randint(
            key, (b, cfg.block_size), 0, cfg.vocab_size
        )
        per_tok = {}
        for mode in ("kv", "exact"):
            t_one = _timed(
                make_sampler(1, sliding=mode), model, prompt_w, key
            )
            t_many = _timed(
                make_sampler(1 + n_slide, sliding=mode), model, prompt_w, key
            )
            per_tok[mode] = max(1e-9, (t_many - t_one) / n_slide)
        kv_per_tok, exact_per_tok = per_tok["kv"], per_tok["exact"]
        record.update(
            {
                "slide_kv_tok_s": round(b / kv_per_tok, 1),
                "slide_exact_tok_s": round(b / exact_per_tok, 1),
                "slide_speedup_kv_vs_exact": round(exact_per_tok / kv_per_tok, 1),
            }
        )
    return record


def main() -> None:
    record = {"device": jax.devices()[0].device_kind}
    record.update(measure_decode(include_sliding=True))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outdir = os.path.join(repo, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, "bench_decode.json"), "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
