"""Microbench: the attention SUB-PATH at the 124M shape, on the real chip.

Quantifies what the projection-natural fused kernel can win (r3): the
current path pays QK-LayerNorm + RoPE (loop fusions, with backward) and
four [B,T,H,C]<->[B,H,T,C] transposes around the flash kernel; the fused
design eliminates all of it. Measures, fwd+bwd each:

  flash_core   pre-transposed [B,H,T,C] q,k,v -> flash -> sum
  full_path    qkv [B,T,(H+2Hkv)C] -> slice/LN/rope/transpose -> flash
               -> transpose back (the real per-layer subgraph)
  naive_path   same but attention via the XLA naive path

full_path - flash_core = the overhead the fused kernel attacks (x n_layer).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

B, T, H, HKV, C = 16, 1024, 12, 12, 64
D = H * C


def _time(fn, *args, n=20):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    # chained: the axon relay makes per-call sync unreliable; time a chain
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    _ = float(jnp.sum(out[0]) if isinstance(out, tuple) else jnp.sum(out))
    return (time.perf_counter() - t0) / n * 1e3


def main():
    from midgpt_tpu.models.layers import LayerNorm, apply_rotary, rope_tables
    from midgpt_tpu.ops.flash import flash_attention

    key = jax.random.PRNGKey(0)
    qkv = jax.random.normal(key, (B, T, (H + 2 * HKV) * C), jnp.bfloat16)
    qp = jax.random.normal(key, (B, H, T, C), jnp.bfloat16)
    kp = jax.random.normal(key, (B, HKV, T, C), jnp.bfloat16)
    vp = jax.random.normal(key, (B, HKV, T, C), jnp.bfloat16)
    sin, cos = rope_tables(C, T)
    q_norm = LayerNorm.init(C)
    k_norm = LayerNorm.init(C)

    def flash_core(q, k, v):
        return flash_attention(q, k, v)

    def full_path(qkv, q_norm, k_norm):
        q = qkv[..., : H * C].reshape(B, T, H, C)
        k = qkv[..., H * C : (H + HKV) * C].reshape(B, T, HKV, C)
        v = qkv[..., (H + HKV) * C :].reshape(B, T, HKV, C)
        q, k = q_norm(q), k_norm(k)
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        q = apply_rotary(q, sin, cos)
        k = apply_rotary(k, sin, cos)
        out = flash_attention(q, k, v)
        return jnp.transpose(out, (0, 2, 1, 3)).reshape(B, T, H * C)

    def naive_core(q, k, v):
        from midgpt_tpu.ops.attention import naive_attention

        return naive_attention(q, k, v, causal=True)

    results = {}
    for name, fn, args in [
        ("flash_core_fwd", flash_core, (qp, kp, vp)),
        ("naive_core_fwd", naive_core, (qp, kp, vp)),
        ("full_path_fwd", functools.partial(full_path), (qkv, q_norm, k_norm)),
    ]:
        results[name] = _time(fn, *args)

    def grad_of(fn, nargs):
        def loss(*a):
            return jnp.sum(fn(*a).astype(jnp.float32))

        return jax.grad(loss, argnums=tuple(range(nargs)))

    results["flash_core_fb"] = _time(grad_of(flash_core, 3), qp, kp, vp)
    results["naive_core_fb"] = _time(grad_of(naive_core, 3), qp, kp, vp)
    results["full_path_fb"] = _time(
        grad_of(lambda a, qn, kn: full_path(a, qn, kn), 1), qkv, q_norm, k_norm
    )

    for k_, v_ in results.items():
        print(f"{k_:>18}: {v_:7.2f} ms")
    print(
        f"\noverhead fwd  (full - flash): {results['full_path_fwd'] - results['flash_core_fwd']:.2f} ms"
    )
    print(
        f"overhead f+b  (full - flash): {results['full_path_fb'] - results['flash_core_fb']:.2f} ms"
    )


if __name__ == "__main__":
    main()
