"""Convert ANY training checkpoint to the int8 quantized serving form.

    python scripts/quantize_ckpt.py --ckpt_dir outputs/run \
        --out outputs/run-int8 [--mode po2]

Restores the ``params`` item of the latest (or ``--step``) checkpoint in
``--ckpt_dir`` (params only — no optimizer state is read), converts every
dense matmul weight to the per-output-channel int8 pytree
(midgpt_tpu.quant.quantize_model), and writes a serving checkpoint to
``--out`` holding a single ``params_q8`` item plus the run's config.json
— loadable by ``sample.py --quant int8`` (and anything calling
``midgpt_tpu.quant.restore_quantized``) with the int8 arrays landing
directly, no full-precision staging.

``--mode po2`` (default) uses power-of-two scales: greedy serving output
is then bit-identical to serving the dequantized weights (the testable
exactness contract); ``--mode absmax`` keeps fractional scales (a ~1-bit
tighter grid, no bitwise contract)."""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--mode", choices=("po2", "absmax"), default="po2")
    from midgpt_tpu.utils.platform_pin import add_platform_arg, apply_platform

    add_platform_arg(ap)
    args = ap.parse_args()
    apply_platform(args.platform)

    import dataclasses

    import jax

    from midgpt_tpu.checkpoint import Checkpointer
    from midgpt_tpu.config import to_dict
    from midgpt_tpu.models.gpt import (
        GPT,
        mlp_hidden_dim,
        pin_mlp_hidden_from_ckpt,
    )
    from midgpt_tpu.quant import QUANT_ITEM, quantize_model
    from sample import load_run_config

    cfg = load_run_config(args.ckpt_dir)
    ckpt = Checkpointer(args.ckpt_dir, save_interval_steps=1)
    cfg = dataclasses.replace(
        cfg, model=pin_mlp_hidden_from_ckpt(cfg.model, ckpt)
    )
    # pin the RESOLVED MLP width into the emitted config: the serving
    # checkpoint holds no "params" item, so a loader re-running the
    # fractional-width pin against it would have no metadata to read —
    # with the width explicit, pin_mlp_hidden_from_ckpt no-ops
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model, mlp_hidden=mlp_hidden_dim(cfg.model)
        ),
    )

    abstract = jax.eval_shape(
        lambda: GPT.init(jax.random.PRNGKey(0), cfg.model)
    )
    items, meta = ckpt.restore({"params": abstract}, step=args.step)
    step = int(meta["step"])
    print(f"restored step {step} from {args.ckpt_dir}")

    qmodel = quantize_model(items["params"], mode=args.mode)

    os.makedirs(args.out, exist_ok=True)
    out_ckpt = Checkpointer(args.out, save_interval_steps=1)
    saved = out_ckpt.save(
        step,
        {QUANT_ITEM: qmodel},
        {"step": step, "quant": "int8-per-channel", "quant_mode": args.mode},
        force=True,
    )
    if not saved:
        # Checkpointer.save no-ops (False) when the step already exists
        # — without this check a re-run with a different --mode would
        # print success while serving the OLD quantization
        raise SystemExit(
            f"--out {args.out} already holds step {step}; delete it or "
            "convert into a fresh directory"
        )
    out_ckpt.close()
    with open(os.path.join(args.out, "config.json"), "w") as f:
        json.dump(to_dict(cfg), f, indent=1)
    from midgpt_tpu.pytree import count_params

    n_int8 = sum(
        leaf.size
        for leaf in jax.tree.leaves(qmodel)
        if leaf.dtype == jax.numpy.int8
    )
    print(
        f"wrote {QUANT_ITEM} (mode={args.mode}) to {args.out}: "
        f"{n_int8 / 1e6:.1f}M int8 weights of "
        f"{count_params(qmodel) / 1e6:.1f}M total params"
    )


if __name__ == "__main__":
    main()
